"""Accurate estimator: node-level MaxAvailableReplicas per member cluster.

The analogue of the karmada-scheduler-estimator server (ref:
pkg/estimator/server/estimate.go:59-112): one estimator instance per member
cluster watches that cluster's nodes/pods and answers
``max available = sum over matching nodes of min_dim((allocatable -
requested) // request)`` with a node-affinity + toleration prefilter and the
allowed-pod headroom per node.

Tensorization: each cluster's node state packs into ``[N, R]`` arrays; a
request batch evaluates as one ``[B, N]`` kernel per cluster. The scheduler
side fans out over estimators and min-merges (client/accurate.go:56-68 —
here a direct call; the gRPC transport wraps this same object in
karmada_tpu.estimator.service).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops  # noqa: F401  — enables x64 before the int64 kernel traces
from ..api.work import ReplicaRequirements

UNAUTHENTIC = -1

#: kill-switch for the batched wire protocol (utils.flags ENV_FLAGS): 0
#: forces every connection onto the per-profile unary fallback — the
#: mixed-version escape hatch and the bench's fallback-parity tier
BATCH_ENV = "KARMADA_TPU_ESTIMATOR_BATCH"
#: seconds a generation confirmation stays trusted across invalidate();
#: 0 re-pings the servers on every invalidated pass
PING_ENV = "KARMADA_TPU_ESTIMATOR_PING_SECONDS"
#: in-flight unary RPCs per server channel on the pipelined fallback path
WIDTH_ENV = "KARMADA_TPU_ESTIMATOR_FALLBACK_WIDTH"


def batch_enabled() -> bool:
    return os.environ.get(BATCH_ENV, "1").lower() not in ("0", "false", "")


def ping_trust_seconds() -> float:
    try:
        return float(os.environ.get(PING_ENV, "0") or 0.0)
    except ValueError:
        return 0.0


def fallback_width() -> int:
    try:
        width = int(os.environ.get(WIDTH_ENV, "4") or 4)
    except ValueError:
        width = 4
    return max(1, width)


def conn_supports_batch(conn) -> Optional[bool]:
    """Per-connection negotiation state: None = not yet probed, False =
    server answered UNIMPLEMENTED (probed once; a reconnect builds a fresh
    connection and re-probes — a wire failure also resets the pin to None
    so a server that dies and returns mid-pass re-negotiates). The env
    kill-switch overrides."""
    if not batch_enabled():
        return False
    return getattr(conn, "supports_batch", None)


def conn_breaker_engaged(conn) -> bool:
    """Is the connection's circuit breaker currently rejecting calls?
    Routing layers consult this BEFORE submitting fan-out work so a
    breaker-open server answers UnauthenticReplica immediately instead of
    burning the executor (and the pass deadline) on a doomed RPC. The
    check is non-consuming — the half-open probe that heals the breaker
    is taken by the transport's own call path, never by routing."""
    br = getattr(conn, "breaker", None)
    return br is not None and br.engaged()


@dataclass
class NodeState:
    """One member node (canonical int units)."""

    name: str
    allocatable: dict[str, int] = field(default_factory=dict)
    requested: dict[str, int] = field(default_factory=dict)  # sum of pod requests
    labels: dict[str, str] = field(default_factory=dict)
    taints: list = field(default_factory=list)  # api.cluster.Taint
    num_pods: int = 0


#: NodeSnapshot generation source: every instance gets a fresh, monotonic
#: generation so a snapshot SWAP (the informer-refresh idiom — build a new
#: NodeSnapshot, assign est.snapshot) always reads as movement to the
#: generation gate. Owners that can prove content equality may carry the
#: old generation forward (controlplane._refresh_estimators does). Offset
#: far above any NodeCache event count so the two generation spaces can
#: never collide for one cluster across a cache<->snapshot swap.
import itertools as _itertools

_SNAPSHOT_GEN = _itertools.count(1 << 32)


class NodeSnapshot:
    """Packed node arrays for one cluster (ref: the lifted kube-scheduler
    NodeInfo snapshot, pkg/util/lifted/scheduler/cache)."""

    def __init__(self, nodes: Sequence[NodeState], dims: Sequence[str]):
        self.nodes = list(nodes)
        self.dims = list(dims)
        self.generation = next(_SNAPSHOT_GEN)
        n, r = len(nodes), len(dims)
        self.available = np.zeros((n, r), np.int64)
        pods_dim = self.dims.index("pods") if "pods" in self.dims else None
        for i, node in enumerate(nodes):
            for j, d in enumerate(self.dims):
                self.available[i, j] = node.allocatable.get(d, 0) - node.requested.get(
                    d, 0
                )
            if pods_dim is not None:
                # allowed pods = allocatable pods - running pods
                # (server/estimate.go:104-112)
                self.available[i, pods_dim] = max(
                    node.allocatable.get("pods", 0) - node.num_pods, 0
                )


class NodeCache:
    """Incrementally-maintained node state for one member cluster.

    Ref: pkg/util/lifted/scheduler/cache/cache.go (AddPod/RemovePod/
    AddNode/RemoveNode/UpdateNode) + server/estimate.go:59-102, where the
    estimator server keeps a kube-scheduler cache incrementally updated
    and snapshots it per request. ``NodeSnapshot`` repacks the full
    [N, R] array from scratch — fine at test scale, wrong shape for a
    10k-node member where every pod event would cost O(N x R). This cache
    mutates packed rows IN PLACE: O(R) per event, stable row ids (a
    freed row is recycled), and the estimator reads the live arrays with
    no copy. Duck-type compatible with ``NodeSnapshot`` (``nodes`` /
    ``dims`` / ``available``), so ``AccurateEstimator`` takes either."""

    def __init__(self, dims: Sequence[str], nodes: Sequence[NodeState] = ()):
        self.dims = list(dims)
        self._pods_dim = (
            self.dims.index("pods") if "pods" in self.dims else None
        )
        self.nodes: list[Optional[NodeState]] = []
        self.available = np.zeros((0, len(self.dims)), np.int64)
        self._rows: dict[str, int] = {}
        self._free: list[int] = []
        self.generation = 0
        for node in nodes:
            self.upsert_node(node)

    def _pack_row(self, i: int, node: NodeState) -> None:
        for j, d in enumerate(self.dims):
            self.available[i, j] = (
                node.allocatable.get(d, 0) - node.requested.get(d, 0)
            )
        if self._pods_dim is not None:
            self.available[i, self._pods_dim] = max(
                node.allocatable.get("pods", 0) - node.num_pods, 0
            )

    def upsert_node(self, node: NodeState) -> None:
        row = self._rows.get(node.name)
        if row is None:
            if self._free:
                row = self._free.pop()
            else:
                row = len(self.nodes)
                self.nodes.append(None)
                if row >= self.available.shape[0]:
                    grown = np.zeros(
                        (max(16, 2 * self.available.shape[0]), len(self.dims)),
                        np.int64,
                    )
                    grown[: self.available.shape[0]] = self.available
                    self.available = grown
            self._rows[node.name] = row
        self.nodes[row] = node
        self._pack_row(row, node)
        self.generation += 1

    def remove_node(self, name: str) -> None:
        row = self._rows.pop(name, None)
        if row is None:
            return
        self.nodes[row] = None
        self.available[row] = 0  # zero rows contribute zero replicas
        self._free.append(row)
        self.generation += 1

    def add_pod(self, node_name: str, requests: Mapping[str, int]) -> None:
        """A pod scheduled onto the node: its requests reduce the node's
        headroom and occupy one pod slot (cache.go AddPod)."""
        row = self._rows.get(node_name)
        if row is None:
            return
        node = self.nodes[row]
        for d, q in requests.items():
            node.requested[d] = node.requested.get(d, 0) + q
        node.num_pods += 1
        self._pack_row(row, node)
        self.generation += 1

    def remove_pod(self, node_name: str, requests: Mapping[str, int]) -> None:
        row = self._rows.get(node_name)
        if row is None:
            return
        node = self.nodes[row]
        for d, q in requests.items():
            node.requested[d] = node.requested.get(d, 0) - q
        node.num_pods = max(0, node.num_pods - 1)
        self._pack_row(row, node)
        self.generation += 1

    def live_nodes(self) -> list[NodeState]:
        return [n for n in self.nodes if n is not None]


def _node_sum_kernel(xp, node_avail, node_ok, requests):
    """node-sum estimate over an array module: min over requested dims of
    floor(avail / request) per node, summed over prefilter-passing nodes,
    int32-clamped. ONE body serves both array modules — jit for real
    batches, plain numpy for SMALL problems, where an estimator server
    answering one unary request (or one cluster's profile rows over a
    handful of nodes) pays more in jit dispatch than the whole estimate
    costs in numpy (~3 ms versus ~50 us per call, which IS the server's
    unary throughput ceiling on small members). Pure int math, so the two
    instantiations are bit-identical by construction (asserted in
    tests/test_estimators.py)."""
    avail = xp.maximum(node_avail, 0)
    per_node = xp.full(
        (requests.shape[0], avail.shape[0]), xp.int64(2**62)
    )
    for r in range(requests.shape[-1]):
        req_r = requests[:, r][:, None]
        ratio = avail[None, :, r] // xp.maximum(req_r, 1)
        per_node = xp.where(req_r > 0, xp.minimum(per_node, ratio), per_node)
    per_node = xp.where(per_node >= 2**62, 0, per_node)  # no requested dims
    total = xp.sum(xp.where(node_ok, per_node, 0), axis=1)
    return xp.minimum(total, xp.int64(2**31 - 1)).astype(xp.int32)


def _node_sum_estimate_np(node_avail, node_ok, requests):
    return _node_sum_kernel(np, node_avail, node_ok, requests)


@jax.jit
def _node_sum_estimate(node_avail, node_ok, requests):
    return _node_sum_kernel(jnp, node_avail, node_ok, requests)


#: below this B x N footprint the numpy mirror beats the jit kernel's
#: dispatch overhead (same crossover idea as the engine's host_small path)
_NP_ESTIMATE_CELLS = 1 << 14


class ResourceQuotaPlugin:
    """Estimate plugin capping replicas by namespace ResourceQuota headroom
    (ref: estimator server mini plugin framework,
    server/framework/interface.go + plugins/resourcequota/resourcequota.go,
    gated by the ResourceQuotaEstimate feature).

    ``quotas`` maps namespace -> {resource: remaining} (canonical units)."""

    def __init__(self, quotas: Optional[dict[str, dict[str, int]]] = None):
        self.quotas = quotas or {}

    def estimate(
        self, namespace: str, requirements: Optional[ReplicaRequirements]
    ) -> Optional[int]:
        """Max replicas the namespace quota still admits; None = no opinion."""
        quota = self.quotas.get(namespace)
        if quota is None or requirements is None:
            return None
        best: Optional[int] = None
        for res, req in requirements.resource_request.items():
            if req <= 0 or res not in quota:
                continue
            fit = max(quota[res], 0) // req
            best = fit if best is None else min(best, fit)
        return best


class AccurateEstimator:
    """Per-cluster node-level estimator service object."""

    def __init__(
        self,
        cluster_name: str,
        snapshot: NodeSnapshot,
        quota_plugin: Optional[ResourceQuotaPlugin] = None,
    ):
        self.cluster_name = cluster_name
        self.snapshot = snapshot
        self.quota_plugin = quota_plugin
        # unschedulable replicas per workload key (fed by the member watcher;
        # ref: server/replica/replica.go:43-77)
        self.unschedulable: dict[str, int] = {}

    def _node_prefilter(
        self, requirements: Optional[ReplicaRequirements]
    ) -> np.ndarray:
        nodes = self.snapshot.nodes
        ok = np.ones(len(nodes), bool)
        if requirements is None or requirements.node_claim is None:
            return ok
        claim = requirements.node_claim
        for i, node in enumerate(nodes):
            if node is None:  # NodeCache hole (removed node)
                ok[i] = False
                continue
            if claim.node_selector:
                if any(node.labels.get(k) != v for k, v in claim.node_selector.items()):
                    ok[i] = False
                    continue
            if node.taints:
                from ..api.cluster import NO_EXECUTE, NO_SCHEDULE, Toleration

                tolerations = [
                    t if isinstance(t, Toleration) else Toleration(**t)
                    for t in claim.tolerations
                ]
                untolerated = any(
                    t.effect in (NO_SCHEDULE, NO_EXECUTE)
                    and not any(tol.tolerates(t) for tol in tolerations)
                    for t in node.taints
                )
                if untolerated:
                    ok[i] = False
        return ok

    def max_available_replicas(
        self,
        requirements: Optional[ReplicaRequirements],
        requests_batch: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """int32[B] for a request batch sharing one node_claim. When
        ``requests_batch`` is None a single row is built from
        ``requirements.resource_request``."""
        if len(self.snapshot.nodes) == 0:
            return np.zeros(
                1 if requests_batch is None else len(requests_batch), np.int32
            )
        if requests_batch is None:
            req = np.zeros((1, len(self.snapshot.dims)), np.int64)
            if requirements is not None:
                for j, d in enumerate(self.snapshot.dims):
                    req[0, j] = requirements.resource_request.get(d, 0)
        else:
            req = np.asarray(requests_batch, np.int64)
        n = len(self.snapshot.nodes)
        node_ok = np.broadcast_to(
            self._node_prefilter(requirements)[None, :], (len(req), n)
        )
        if len(req) * n <= _NP_ESTIMATE_CELLS:
            out = _node_sum_estimate_np(
                # trim to the row count: a NodeCache over-allocates
                np.asarray(self.snapshot.available[:n]), node_ok, req
            )
        else:
            out = np.asarray(
                _node_sum_estimate(
                    jnp.asarray(self.snapshot.available[:n]),
                    jnp.asarray(node_ok),
                    jnp.asarray(req),
                )
            )
        # quota plugin caps the node-sum estimate (server/estimate.go:98-101,
        # RunEstimateReplicasPlugins min-merge), feature-gated
        from ..utils.features import RESOURCE_QUOTA_ESTIMATE, feature_gate

        if (
            self.quota_plugin is not None
            and requirements is not None
            and feature_gate.enabled(RESOURCE_QUOTA_ESTIMATE)
        ):
            cap = self.quota_plugin.estimate(requirements.namespace, requirements)
            if cap is not None:
                out = np.minimum(out, np.int32(cap))
        return out

    def get_unschedulable_replicas(self, workload_key: str) -> int:
        """Ref: server GetUnschedulableReplicas; counts come from the member
        watcher's pod conditions."""
        return self.unschedulable.get(workload_key, 0)


class EstimatorRegistry:
    """Scheduler-side estimator fan-out (ref: client/accurate.go:33-68 — the
    per-cluster connection cache + concurrent fan-out), batch-native and
    delta-aware.

    Estimates memoize per (cluster, unique request profile) and are GATED
    by the owning estimator's snapshot generation: ``invalidate()`` marks
    every cluster unconfirmed, and the next pass re-confirms them with one
    GetGenerations ping per SERVER connection — only clusters whose
    generation actually advanced re-pay the profile fan-out, and the
    fan-out itself is one MaxAvailableReplicasBatch per server instead of
    clusters x profiles unary calls. Old servers (UNIMPLEMENTED) keep the
    reference shape: full per-cluster re-query on every invalidation,
    pipelined over the channel."""

    def __init__(self) -> None:
        self._by_cluster: dict[str, AccurateEstimator] = {}
        self._pool = None
        # wall seconds spent in live estimator traffic (generation pings +
        # memo-miss fan-outs) since construction — benches diff this across
        # passes to report the snapshot-refresh latency of estimator-backed
        # availability
        self.fanout_seconds_total = 0.0
        # memoized answers, one scalar per (cluster, profile bytes); the
        # profile key is positional over the engine snapshot's dims, so one
        # registry serves one dims universe at a time (as before)
        self._memo: dict[tuple[str, bytes], int] = {}
        # last generation each cluster's memo entries were computed at
        self._gen: dict[str, int] = {}
        # clusters whose memo is trusted this epoch -> monotonic confirm
        # time (the PING_ENV trust window keys off it)
        self._confirmed: dict[str, float] = {}
        # live RPCs issued since construction, by kind — benches diff this
        # per pass to prove the O(servers) steady-pass shape
        self.rpc_counts: dict[str, int] = {"batch": 0, "unary": 0, "ping": 0}
        # memo-content version: bumped whenever an entry is written or
        # dropped. confirm_token() folds it into the token the scheduler's
        # batch-identity fast path compares — equal tokens prove the
        # estimator contribution to a replayed batch is unchanged
        self._epoch = 0

    def _count_rpc(self, kind: str, n: int = 1) -> None:
        """One choke point for wire accounting: the per-registry
        ``rpc_counts`` dict (benches diff it per pass) AND the process
        metric family (karmada_tpu_estimator_rpcs_total) move together so
        the two surfaces can never disagree."""
        from ..utils.metrics import estimator_rpcs

        self.rpc_counts[kind] += n
        estimator_rpcs.inc(n, kind=kind)

    def register(self, est: AccurateEstimator) -> None:
        self._by_cluster[est.cluster_name] = est
        # a (re)registered estimator invalidates exactly its own cluster's
        # memo — columns are keyed by name, so other members keep theirs
        self._drop_cluster(est.cluster_name)

    def deregister(self, cluster_name: str) -> None:
        self._by_cluster.pop(cluster_name, None)
        self._drop_cluster(cluster_name)

    def _drop_cluster(self, name: str) -> None:
        self._gen.pop(name, None)
        self._confirmed.pop(name, None)
        self._epoch += 1
        for key in [k for k in self._memo if k[0] == name]:
            del self._memo[key]

    def get(self, cluster_name: str) -> Optional[AccurateEstimator]:
        return self._by_cluster.get(cluster_name)

    def invalidate(self, drop: bool = False) -> None:
        """Mark memoized estimates stale. Staleness contract: an estimate
        is a point-in-time answer memoized per (cluster, profile) until the
        owner observes member state change (cluster status heartbeat /
        snapshot swap) and invalidates. The default is GENERATION-GATED:
        memo entries survive, and the next pass re-confirms each cluster's
        snapshot generation (one ping per server) — a no-movement refresh
        never touches the profile fan-out. ``drop=True`` is the hard form
        (membership changes, tests, benches): forget everything and re-pay
        the full fan-out next pass."""
        if drop:
            self._memo.clear()
            self._gen.clear()
            self._confirmed.clear()
            self._epoch += 1
            return
        trust = ping_trust_seconds()
        if trust <= 0:
            self._confirmed.clear()
            return
        import time as _time

        now = _time.monotonic()
        self._confirmed = {
            c: t for c, t in self._confirmed.items() if now - t < trust
        }

    def make_batch_estimator(
        self,
        cluster_names: Sequence[str],
        *,
        max_workers: int = 64,
        timeout_seconds: Optional[float] = None,
    ):
        """Adapter for TensorScheduler.extra_estimators: returns
        fn(requests[B,R], replicas[B]) -> int32[B,C] with -1 where no
        estimator serves the cluster.

        Fan-out is CONCURRENT under one shared deadline
        (client/accurate.go:139-162), grouped by server connection: one
        batch RPC per server covers every hosted cluster's misses; clusters
        on fallback (unary) connections fan out per cluster with pipelined
        per-profile calls. A cluster missing the deadline answers
        UnauthenticReplica (-1) for this pass, so the min-merge ignores it
        instead of blocking scheduling — its late result is discarded,
        never applied to a later pass, and (per-column completeness) it
        never blocks memoization of the clusters that did answer."""
        names = list(cluster_names)
        # registered clusters the LAST estimate pass answered -1 for
        # (unconfirmed or cells missing): such a pass is degraded and must
        # never be replayed by the scheduler's batch-identity fast path —
        # the cluster may become confirmable right after (its server
        # recovers), at which point a replayed pass would pin the
        # transient -1 forever while a real pass would answer from memo
        unanswered: set = set()

        def estimate(requests: np.ndarray, replicas: np.ndarray) -> np.ndarray:
            reqs = np.asarray(requests)
            reps = np.asarray(replicas)
            out = np.full((len(reqs), len(names)), UNAUTHENTIC, np.int32)
            # zero-replica rows (the engine's power-of-two PAD rows, plus
            # real scale-to-zero bindings) never need a live answer — the
            # min-merge ignores -1 and the divider assigns 0 regardless, so
            # their profiles must not force a wire wave of their own
            live = reps > 0
            if not live.any():
                return out
            uniq, inv = np.unique(reqs[live], axis=0, return_inverse=True)
            prof_keys = [row.tobytes() for row in uniq]
            self._refresh(names, uniq, prof_keys, max_workers, timeout_seconds)
            table = np.full((len(uniq), len(names)), UNAUTHENTIC, np.int32)
            memo = self._memo
            unanswered.clear()
            for ci, name in enumerate(names):
                # clusters with no registered estimator answer -1
                # STRUCTURALLY (deterministic); unconfirmed clusters answer
                # -1 for this pass only
                if name not in self._confirmed:
                    if name in self._by_cluster:
                        unanswered.add(name)
                    continue
                for u, key in enumerate(prof_keys):
                    val = memo.get((name, key))
                    if val is not None:
                        table[u, ci] = val
                    else:
                        unanswered.add(name)
            out[live] = table[inv]
            if unanswered:
                # degraded pass: at least one registered cluster answered
                # -1 transiently. Observable (the counter) and never
                # replayable (refresh_token below answers None).
                from ..utils.metrics import degraded_passes

                degraded_passes.inc(channel="estimator")
            return out

        def refresh_token():
            # the scheduler's batch-identity fast path probes this before
            # replaying a storm pass: it confirms generations (O(servers)
            # pings) and returns an unchanged token iff no memo content
            # moved AND the last pass answered every registered cluster —
            # a degraded pass (transient -1 cells) is never replayable
            token = self.confirm_token(
                names, max_workers=max_workers,
                timeout_seconds=timeout_seconds,
            )
            if token is None or unanswered:
                return None
            return token

        estimate.refresh_token = refresh_token
        return estimate

    # -- live refresh machinery (ping + grouped fan-out) -------------------

    def _refresh(
        self,
        names: Sequence[str],
        uniq: np.ndarray,
        prof_keys: Sequence[bytes],
        max_workers: int,
        timeout_seconds: Optional[float],
    ) -> None:
        """Bring every (cluster, profile) memo cell either up to date or
        provably unanswerable for this pass. Mutates memo/generation state
        only on the calling thread — pool tasks just return data."""
        import time as _time

        from ..utils.metrics import (
            estimator_delta_requeries,
            estimator_refresh_seconds,
        )
        from ..utils.tracing import tracer

        t0 = _time.perf_counter()
        deadline = (
            None if timeout_seconds is None else t0 + timeout_seconds
        )

        def remaining() -> Optional[float]:
            if deadline is None:
                return None
            return max(deadline - _time.perf_counter(), 0.0)

        with tracer.span("estimator.refresh") as sp:
            # steps A+B: confirm generations (local reads + one ping per
            # server connection)
            touched_wire = self._confirm_generations(
                names, prof_keys, max_workers, remaining
            )

            # ---- step C: fetch — clusters with any unmemoized profile,
            # grouped by batch-capable connection; the rest per cluster
            fetch: list = []  # (name, est, conn | None)
            for name in names:
                est = self._by_cluster.get(name)
                if est is None:
                    continue
                if name in self._confirmed and all(
                    (name, k) in self._memo for k in prof_keys
                ):
                    continue
                fetch.append((name, est, getattr(est, "conn", None)))
            sp.attrs["requeried_clusters"] = len(fetch)
            if fetch:
                touched_wire = True
                # the delta half of the generation-gated refresh: only
                # clusters whose generation moved (or never fetched)
                # re-pay the fan-out — this counter is that cardinality
                estimator_delta_requeries.inc(len(fetch))
                self._fetch(fetch, uniq, prof_keys, max_workers, remaining)
        if touched_wire:
            elapsed = _time.perf_counter() - t0
            self.fanout_seconds_total += elapsed
            estimator_refresh_seconds.observe(elapsed)

    def _confirm_generations(
        self,
        names: Sequence[str],
        prof_keys: Optional[Sequence[bytes]],
        max_workers: int,
        remaining,
    ) -> bool:
        """Confirm every unconfirmed cluster's snapshot generation: local
        estimators by a direct read, remote ones with one GetGenerations
        ping per server connection. A cluster whose generation moved drops
        its memo (the fetch step re-queries it). When ``prof_keys`` is
        given, remote clusters with ANY unmemoized profile skip the ping —
        the fetch returns their generation anyway; ``prof_keys=None``
        (confirm_token) pings every unconfirmed remote. Returns True when
        any wire traffic happened."""
        from concurrent.futures import wait as _fwait

        from .service import UnsupportedMethodError

        # ---- step A: local estimators confirm by direct generation read
        remote_unconfirmed: list = []  # (name, est, conn)
        for name in names:
            if name in self._confirmed:
                continue
            est = self._by_cluster.get(name)
            if est is None:
                continue
            conn = getattr(est, "conn", None)
            if conn is None:
                gen = int(getattr(est.snapshot, "generation", 0))
                if self._gen.get(name) != gen:
                    self._drop_cluster(name)
                    self._gen[name] = gen
                self._confirm(name)
                continue
            remote_unconfirmed.append((name, est, conn))

        # ---- step B: generation pings, one per server connection
        ping_groups: dict[int, tuple] = {}
        for name, est, conn in remote_unconfirmed:
            if conn_breaker_engaged(conn):
                # breaker-open server: stay unconfirmed (-1 this pass)
                # WITHOUT submitting the doomed ping; the memo survives,
                # so the half-open probe that heals the channel
                # revalidates it without a refetch
                continue
            if prof_keys is not None and not all(
                (name, k) in self._memo for k in prof_keys
            ):
                continue
            if conn_supports_batch(conn) is False:
                # old server: no generations to ask for — re-pay the
                # fan-out for this cluster (the reference's shape)
                self._drop_cluster(name)
                continue
            key = id(conn)
            if key not in ping_groups:
                ping_groups[key] = (conn, [])
            ping_groups[key][1].append(name)
        if not ping_groups:
            return False
        from .service import GetGenerationsRequest

        pool = self._ensure_pool(max_workers)

        def ping(conn, members):
            return conn.call(
                "GetGenerations", GetGenerationsRequest(clusters=members)
            )

        futs = {}
        for conn, members in ping_groups.values():
            self._count_rpc("ping")
            futs[pool.submit(ping, conn, list(members))] = (conn, members)
        done, not_done = _fwait(futs, timeout=remaining())
        for f in not_done:
            f.cancel()  # members stay unconfirmed: -1 this pass
        for f in done:
            conn, members = futs[f]
            try:
                resp = f.result()
            except UnsupportedMethodError:
                conn.supports_batch = False
                for name in members:
                    self._drop_cluster(name)  # refetch on the unary path
                continue
            except Exception:  # noqa: BLE001 — server unreachable:
                # members stay unconfirmed (and answer -1) this pass;
                # the memo survives, so a later ping that finds the
                # generation unchanged revalidates it without a refetch
                continue
            for name in members:
                gen = resp.generations.get(name)
                if gen is not None and self._gen.get(name) == gen:
                    self._confirm(name)
                else:
                    self._drop_cluster(name)  # moved (or unknown)
        return True

    def confirm_token(
        self,
        cluster_names: Sequence[str],
        *,
        max_workers: int = 64,
        timeout_seconds: Optional[float] = None,
    ):
        """Prove the estimator contribution to a scheduling batch is
        unchanged, as cheaply as the protocol allows: confirm every
        registered cluster's snapshot generation (O(servers) pings; zero
        wire when everything is already confirmed) and return an opaque
        token that is EQUAL to a previous token iff no memo content
        changed in between. Returns None when any registered cluster could
        not be confirmed (old server, unreachable, or never fetched) — the
        caller must run the full estimate path, which retries those
        clusters. The scheduler's batch-identity fast path compares tokens
        to replay a storm pass without re-solving it."""
        import time as _time

        names = list(cluster_names)
        t0 = _time.perf_counter()
        deadline = (
            None if timeout_seconds is None else t0 + timeout_seconds
        )

        def remaining() -> Optional[float]:
            if deadline is None:
                return None
            return max(deadline - _time.perf_counter(), 0.0)

        touched = self._confirm_generations(names, None, max_workers, remaining)
        if touched:
            self.fanout_seconds_total += _time.perf_counter() - t0
        if all(
            name in self._confirmed
            for name in names
            if name in self._by_cluster
        ):
            return (self._epoch,)
        return None

    def _confirm(self, name: str) -> None:
        import time as _time

        self._confirmed[name] = _time.monotonic()

    def _ensure_pool(self, max_workers: int):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            from ..utils.tracing import ContextPropagatingExecutor

            # context-propagating: ping/fetch tasks open their RPC spans
            # under the refresh span that submitted them (estimator.rpc
            # must not land in wave 0 on a bare pool thread)
            self._pool = ContextPropagatingExecutor(
                ThreadPoolExecutor(max_workers)
            )
        return self._pool

    def _fetch(self, fetch, uniq, prof_keys, max_workers, remaining) -> None:
        """One batch RPC per batch-capable server connection; per-CHANNEL
        pipelined unary tasks for fallback servers; per-cluster tasks for
        local estimators. Results merge on the calling thread: a cluster
        that answered memoizes regardless of what happened to any other
        cluster (per-column completeness). Only the profile columns some
        fetched cluster is actually missing go over the wire — a pass whose
        only novelty is one new profile ships one row, not the matrix."""
        from concurrent.futures import wait as _fwait

        from .service import UnsupportedMethodError

        pool = self._ensure_pool(max_workers)
        # an unconfirmed cluster cannot trust ANY memo entry (its
        # generation is unknown), so it needs the full matrix; confirmed
        # clusters only their missing columns
        miss_idx: set = set()
        for name, _est, _conn in fetch:
            if name not in self._confirmed:
                miss_idx = set(range(len(prof_keys)))
                break
            miss_idx.update(
                u
                for u, k in enumerate(prof_keys)
                if (name, k) not in self._memo
            )
        order = sorted(miss_idx)
        sub_uniq = np.asarray(uniq)[order]
        sub_keys = [prof_keys[u] for u in order]
        rows = [[int(v) for v in row] for row in sub_uniq]

        batch_groups: dict[int, tuple] = {}  # id(conn) -> (conn, members)
        unary_groups: dict[int, tuple] = {}  # id(conn) -> (conn, members)
        locals_: list = []  # (name, est) — no connection (in-proc direct)
        retry: list = []  # members re-routed after a mid-pass UNIMPLEMENTED

        def route(name, est, conn):
            if conn is not None and conn_breaker_engaged(conn):
                # breaker-open server: the cluster answers -1 for this
                # pass with ZERO executor/wire cost (stays unconfirmed,
                # so the pass is degraded and never replayable)
                return
            if conn is not None and conn_supports_batch(conn) is not False:
                batch_groups.setdefault(id(conn), (conn, []))[1].append(
                    (name, est)
                )
            elif conn is not None and hasattr(conn, "call_future"):
                unary_groups.setdefault(id(conn), (conn, []))[1].append(
                    (name, est)
                )
            else:
                locals_.append((name, est))

        for name, est, conn in fetch:
            route(name, est, conn)

        def fetch_batch(conn, members):
            # NOTE: the registry's profile matrix is np.unique'd ACROSS
            # namespaces, so this path sends no per-row namespaces — the
            # server's ResourceQuota plugin stays inert here exactly as
            # it does on the registry's unary fallback (which also sends
            # namespace=""). Namespace-aware callers that want the
            # member-quota cap populate MaxAvailableReplicasBatchRequest.
            # namespaces per row; wire parity with the unary path is
            # asserted in tests/test_estimator_batch.py.
            from .service import MaxAvailableReplicasBatchRequest

            dims = list(members[0][1].dims_provider())
            return conn.call(
                "MaxAvailableReplicasBatch",
                MaxAvailableReplicasBatchRequest(
                    clusters=[name for name, _ in members],
                    dims=dims,
                    rows=rows,
                ),
            )

        def fetch_unary_channel(conn, members):
            """The pipelined fallback: ONE task per server channel slides a
            bounded window of per-profile calls over it (grpc futures) —
            latency hides without flooding the connection's HTTP/2 stream
            limit the way a task per cluster would."""
            from collections import deque

            from .service import MaxAvailableReplicasRequest

            width = fallback_width()
            out = {
                name: np.full(len(rows), UNAUTHENTIC, np.int32)
                for name, _ in members
            }

            def resolve(entry):
                name, u, fut = entry
                try:
                    out[name][u] = fut.result().max_replicas
                except Exception:  # noqa: BLE001 — per-RPC failure = -1
                    pass

            inflight: deque = deque()
            for name, est in members:
                dims = list(est.dims_provider())
                for u, row in enumerate(sub_uniq):
                    req = MaxAvailableReplicasRequest(
                        cluster=name,
                        resource_request={
                            d: int(q) for d, q in zip(dims, row) if q > 0
                        },
                    )
                    if len(inflight) >= width:
                        resolve(inflight.popleft())
                    try:
                        inflight.append(
                            (name, u,
                             conn.call_future("MaxAvailableReplicas", req))
                        )
                    except Exception:  # noqa: BLE001 — submit failure = -1
                        pass
            while inflight:
                resolve(inflight.popleft())
            return out

        def fetch_single(name, est):
            conn = getattr(est, "conn", None)
            if conn is not None and hasattr(est, "query_profiles"):
                dims = list(est.dims_provider())
                return est.query_profiles(dims, sub_uniq)
            # local estimator: generation read BEFORE computing so a
            # concurrent member event makes the answer look stale (see
            # EstimatorService.max_available_replicas_batch)
            gen = int(getattr(est.snapshot, "generation", 0))
            return (
                np.asarray(
                    est.max_available_replicas(None, sub_uniq), np.int32
                ),
                gen,
            )

        def merge_vals(name, vals, gen) -> None:
            if np.asarray(vals).min(initial=0) < 0:
                # the adapter reports per-RPC wire failures as -1 rows —
                # transient, never memoized (a pinned -1 would shadow the
                # member until the next hard invalidation)
                return
            self._memoize(name, sub_keys, vals, gen)

        futs = {}
        for conn, members in batch_groups.values():
            self._count_rpc("batch")
            futs[pool.submit(fetch_batch, conn, members)] = (
                "batch", (conn, members),
            )
        for conn, members in unary_groups.values():
            self._count_rpc("unary", len(members) * len(rows))
            futs[pool.submit(fetch_unary_channel, conn, members)] = (
                "unary", (conn, members),
            )
        for name, est in locals_:
            if getattr(est, "conn", None) is not None:
                self._count_rpc("unary", len(rows))
            futs[pool.submit(fetch_single, name, est)] = ("single", name)
        done, not_done = _fwait(futs, timeout=remaining())
        for f in not_done:
            # a straggler answers -1 this pass only (it stays unconfirmed
            # and unmemoized) — per-column completeness: it cannot block
            # the clusters that DID answer from memoizing
            f.cancel()
        for f in done:
            kind, meta = futs[f]
            try:
                result = f.result()
            except UnsupportedMethodError:
                if kind == "batch":
                    # negotiated mid-pass: pin the fallback on the
                    # connection (the gRPC conn already did; the in-proc
                    # seam needs it set here) and re-fan these clusters
                    # over the unary path — once per connection lifetime
                    conn, members = meta
                    conn.supports_batch = False
                    retry.append((conn, members))
                continue
            except Exception:  # noqa: BLE001 — wire failure = -1 this pass
                continue
            if kind == "batch":
                _conn, members = meta
                answered = {res.cluster: res for res in result.results}
                for name, _est in members:
                    res = answered.get(name)
                    if res is None:
                        continue  # unhosted: structural -1, never memoized
                    self._memoize(
                        name, sub_keys, res.max_replicas, res.generation
                    )
            elif kind == "unary":
                for name, vals in result.items():
                    merge_vals(name, vals, None)
            else:
                vals, gen = result
                merge_vals(meta, vals, gen)
        if retry:
            futs = {}
            for conn, members in retry:
                if hasattr(conn, "call_future"):
                    self._count_rpc("unary", len(members) * len(rows))
                    futs[pool.submit(fetch_unary_channel, conn, members)] = (
                        "unary", (conn, members),
                    )
                else:
                    for name, est in members:
                        self._count_rpc("unary", len(rows))
                        futs[pool.submit(fetch_single, name, est)] = (
                            "single", name,
                        )
            done, not_done = _fwait(futs, timeout=remaining())
            for f in not_done:
                f.cancel()
            for f in done:
                kind, meta = futs[f]
                try:
                    result = f.result()
                except Exception:  # noqa: BLE001
                    continue
                if kind == "unary":
                    for name, vals in result.items():
                        merge_vals(name, vals, None)
                else:
                    vals, gen = result
                    merge_vals(meta, vals, gen)

    def _memoize(self, name, prof_keys, values, gen) -> None:
        if gen is not None and self._gen.get(name) not in (None, int(gen)):
            # the server's snapshot moved between our last fetch and this
            # partial one: entries OUTSIDE this response are at the old
            # generation — drop them so they re-fetch instead of serving
            # stale values next to fresh ones
            self._drop_cluster(name)
        self._epoch += 1
        for key, val in zip(prof_keys, values):
            self._memo[(name, key)] = int(val)
        if gen is not None:
            self._gen[name] = int(gen)
        else:
            # fallback server: no generation protocol — entries stay valid
            # until the next invalidate() epoch, then re-fetch (the
            # reference's full-refresh shape)
            self._gen.pop(name, None)
        self._confirm(name)
