"""Accurate estimator: node-level MaxAvailableReplicas per member cluster.

The analogue of the karmada-scheduler-estimator server (ref:
pkg/estimator/server/estimate.go:59-112): one estimator instance per member
cluster watches that cluster's nodes/pods and answers
``max available = sum over matching nodes of min_dim((allocatable -
requested) // request)`` with a node-affinity + toleration prefilter and the
allowed-pod headroom per node.

Tensorization: each cluster's node state packs into ``[N, R]`` arrays; a
request batch evaluates as one ``[B, N]`` kernel per cluster. The scheduler
side fans out over estimators and min-merges (client/accurate.go:56-68 —
here a direct call; the gRPC transport wraps this same object in
karmada_tpu.estimator.service).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops  # noqa: F401  — enables x64 before the int64 kernel traces
from ..api.work import ReplicaRequirements

UNAUTHENTIC = -1


@dataclass
class NodeState:
    """One member node (canonical int units)."""

    name: str
    allocatable: dict[str, int] = field(default_factory=dict)
    requested: dict[str, int] = field(default_factory=dict)  # sum of pod requests
    labels: dict[str, str] = field(default_factory=dict)
    taints: list = field(default_factory=list)  # api.cluster.Taint
    num_pods: int = 0


class NodeSnapshot:
    """Packed node arrays for one cluster (ref: the lifted kube-scheduler
    NodeInfo snapshot, pkg/util/lifted/scheduler/cache)."""

    def __init__(self, nodes: Sequence[NodeState], dims: Sequence[str]):
        self.nodes = list(nodes)
        self.dims = list(dims)
        n, r = len(nodes), len(dims)
        self.available = np.zeros((n, r), np.int64)
        pods_dim = self.dims.index("pods") if "pods" in self.dims else None
        for i, node in enumerate(nodes):
            for j, d in enumerate(self.dims):
                self.available[i, j] = node.allocatable.get(d, 0) - node.requested.get(
                    d, 0
                )
            if pods_dim is not None:
                # allowed pods = allocatable pods - running pods
                # (server/estimate.go:104-112)
                self.available[i, pods_dim] = max(
                    node.allocatable.get("pods", 0) - node.num_pods, 0
                )


class NodeCache:
    """Incrementally-maintained node state for one member cluster.

    Ref: pkg/util/lifted/scheduler/cache/cache.go (AddPod/RemovePod/
    AddNode/RemoveNode/UpdateNode) + server/estimate.go:59-102, where the
    estimator server keeps a kube-scheduler cache incrementally updated
    and snapshots it per request. ``NodeSnapshot`` repacks the full
    [N, R] array from scratch — fine at test scale, wrong shape for a
    10k-node member where every pod event would cost O(N x R). This cache
    mutates packed rows IN PLACE: O(R) per event, stable row ids (a
    freed row is recycled), and the estimator reads the live arrays with
    no copy. Duck-type compatible with ``NodeSnapshot`` (``nodes`` /
    ``dims`` / ``available``), so ``AccurateEstimator`` takes either."""

    def __init__(self, dims: Sequence[str], nodes: Sequence[NodeState] = ()):
        self.dims = list(dims)
        self._pods_dim = (
            self.dims.index("pods") if "pods" in self.dims else None
        )
        self.nodes: list[Optional[NodeState]] = []
        self.available = np.zeros((0, len(self.dims)), np.int64)
        self._rows: dict[str, int] = {}
        self._free: list[int] = []
        self.generation = 0
        for node in nodes:
            self.upsert_node(node)

    def _pack_row(self, i: int, node: NodeState) -> None:
        for j, d in enumerate(self.dims):
            self.available[i, j] = (
                node.allocatable.get(d, 0) - node.requested.get(d, 0)
            )
        if self._pods_dim is not None:
            self.available[i, self._pods_dim] = max(
                node.allocatable.get("pods", 0) - node.num_pods, 0
            )

    def upsert_node(self, node: NodeState) -> None:
        row = self._rows.get(node.name)
        if row is None:
            if self._free:
                row = self._free.pop()
            else:
                row = len(self.nodes)
                self.nodes.append(None)
                if row >= self.available.shape[0]:
                    grown = np.zeros(
                        (max(16, 2 * self.available.shape[0]), len(self.dims)),
                        np.int64,
                    )
                    grown[: self.available.shape[0]] = self.available
                    self.available = grown
            self._rows[node.name] = row
        self.nodes[row] = node
        self._pack_row(row, node)
        self.generation += 1

    def remove_node(self, name: str) -> None:
        row = self._rows.pop(name, None)
        if row is None:
            return
        self.nodes[row] = None
        self.available[row] = 0  # zero rows contribute zero replicas
        self._free.append(row)
        self.generation += 1

    def add_pod(self, node_name: str, requests: Mapping[str, int]) -> None:
        """A pod scheduled onto the node: its requests reduce the node's
        headroom and occupy one pod slot (cache.go AddPod)."""
        row = self._rows.get(node_name)
        if row is None:
            return
        node = self.nodes[row]
        for d, q in requests.items():
            node.requested[d] = node.requested.get(d, 0) + q
        node.num_pods += 1
        self._pack_row(row, node)
        self.generation += 1

    def remove_pod(self, node_name: str, requests: Mapping[str, int]) -> None:
        row = self._rows.get(node_name)
        if row is None:
            return
        node = self.nodes[row]
        for d, q in requests.items():
            node.requested[d] = node.requested.get(d, 0) - q
        node.num_pods = max(0, node.num_pods - 1)
        self._pack_row(row, node)
        self.generation += 1

    def live_nodes(self) -> list[NodeState]:
        return [n for n in self.nodes if n is not None]


@jax.jit
def _node_sum_estimate(
    node_avail: jnp.ndarray,  # int64[N, R]
    node_ok: jnp.ndarray,  # bool[B, N] affinity/toleration prefilter
    requests: jnp.ndarray,  # int64[B, R]
) -> jnp.ndarray:
    avail = jnp.maximum(node_avail, 0)
    r_dims = requests.shape[-1]
    per_node = jnp.full((requests.shape[0], avail.shape[0]), jnp.int64(2**62))
    for r in range(r_dims):
        req_r = requests[:, r][:, None]
        ratio = avail[None, :, r] // jnp.maximum(req_r, 1)
        per_node = jnp.where(req_r > 0, jnp.minimum(per_node, ratio), per_node)
    per_node = jnp.where(per_node >= 2**62, 0, per_node)  # no requested dims
    total = jnp.sum(jnp.where(node_ok, per_node, 0), axis=1)
    return jnp.minimum(total, jnp.int64(2**31 - 1)).astype(jnp.int32)


class ResourceQuotaPlugin:
    """Estimate plugin capping replicas by namespace ResourceQuota headroom
    (ref: estimator server mini plugin framework,
    server/framework/interface.go + plugins/resourcequota/resourcequota.go,
    gated by the ResourceQuotaEstimate feature).

    ``quotas`` maps namespace -> {resource: remaining} (canonical units)."""

    def __init__(self, quotas: Optional[dict[str, dict[str, int]]] = None):
        self.quotas = quotas or {}

    def estimate(
        self, namespace: str, requirements: Optional[ReplicaRequirements]
    ) -> Optional[int]:
        """Max replicas the namespace quota still admits; None = no opinion."""
        quota = self.quotas.get(namespace)
        if quota is None or requirements is None:
            return None
        best: Optional[int] = None
        for res, req in requirements.resource_request.items():
            if req <= 0 or res not in quota:
                continue
            fit = max(quota[res], 0) // req
            best = fit if best is None else min(best, fit)
        return best


class AccurateEstimator:
    """Per-cluster node-level estimator service object."""

    def __init__(
        self,
        cluster_name: str,
        snapshot: NodeSnapshot,
        quota_plugin: Optional[ResourceQuotaPlugin] = None,
    ):
        self.cluster_name = cluster_name
        self.snapshot = snapshot
        self.quota_plugin = quota_plugin
        # unschedulable replicas per workload key (fed by the member watcher;
        # ref: server/replica/replica.go:43-77)
        self.unschedulable: dict[str, int] = {}

    def _node_prefilter(
        self, requirements: Optional[ReplicaRequirements]
    ) -> np.ndarray:
        nodes = self.snapshot.nodes
        ok = np.ones(len(nodes), bool)
        if requirements is None or requirements.node_claim is None:
            return ok
        claim = requirements.node_claim
        for i, node in enumerate(nodes):
            if node is None:  # NodeCache hole (removed node)
                ok[i] = False
                continue
            if claim.node_selector:
                if any(node.labels.get(k) != v for k, v in claim.node_selector.items()):
                    ok[i] = False
                    continue
            if node.taints:
                from ..api.cluster import NO_EXECUTE, NO_SCHEDULE, Toleration

                tolerations = [
                    t if isinstance(t, Toleration) else Toleration(**t)
                    for t in claim.tolerations
                ]
                untolerated = any(
                    t.effect in (NO_SCHEDULE, NO_EXECUTE)
                    and not any(tol.tolerates(t) for tol in tolerations)
                    for t in node.taints
                )
                if untolerated:
                    ok[i] = False
        return ok

    def max_available_replicas(
        self,
        requirements: Optional[ReplicaRequirements],
        requests_batch: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """int32[B] for a request batch sharing one node_claim. When
        ``requests_batch`` is None a single row is built from
        ``requirements.resource_request``."""
        if len(self.snapshot.nodes) == 0:
            return np.zeros(
                1 if requests_batch is None else len(requests_batch), np.int32
            )
        if requests_batch is None:
            req = np.zeros((1, len(self.snapshot.dims)), np.int64)
            if requirements is not None:
                for j, d in enumerate(self.snapshot.dims):
                    req[0, j] = requirements.resource_request.get(d, 0)
        else:
            req = np.asarray(requests_batch, np.int64)
        n = len(self.snapshot.nodes)
        node_ok = np.broadcast_to(
            self._node_prefilter(requirements)[None, :], (len(req), n)
        )
        out = np.asarray(
            _node_sum_estimate(
                # trim to the row count: a NodeCache over-allocates
                jnp.asarray(self.snapshot.available[:n]),
                jnp.asarray(node_ok),
                jnp.asarray(req),
            )
        )
        # quota plugin caps the node-sum estimate (server/estimate.go:98-101,
        # RunEstimateReplicasPlugins min-merge), feature-gated
        from ..utils.features import RESOURCE_QUOTA_ESTIMATE, feature_gate

        if (
            self.quota_plugin is not None
            and requirements is not None
            and feature_gate.enabled(RESOURCE_QUOTA_ESTIMATE)
        ):
            cap = self.quota_plugin.estimate(requirements.namespace, requirements)
            if cap is not None:
                out = np.minimum(out, np.int32(cap))
        return out

    def get_unschedulable_replicas(self, workload_key: str) -> int:
        """Ref: server GetUnschedulableReplicas; counts come from the member
        watcher's pod conditions."""
        return self.unschedulable.get(workload_key, 0)


class EstimatorRegistry:
    """Scheduler-side estimator fan-out (ref: client/accurate.go:33-68 — the
    per-cluster connection cache + concurrent fan-out)."""

    def __init__(self) -> None:
        self._by_cluster: dict[str, AccurateEstimator] = {}
        self._pool = None
        # wall seconds spent in live estimator fan-outs (memo misses) since
        # construction — benches diff this across passes to report the
        # snapshot-refresh latency of estimator-backed availability
        self.fanout_seconds_total = 0.0
        self._memo: dict[tuple, np.ndarray] = {}

    def register(self, est: AccurateEstimator) -> None:
        self._by_cluster[est.cluster_name] = est
        # memoized columns are positional over a batch estimator's name
        # list; any membership change invalidates them (a stale shorter
        # column would shape-mismatch a rebuilt, longer fan-out)
        self._memo.clear()

    def deregister(self, cluster_name: str) -> None:
        self._by_cluster.pop(cluster_name, None)
        self._memo.clear()

    def get(self, cluster_name: str) -> Optional[AccurateEstimator]:
        return self._by_cluster.get(cluster_name)

    def invalidate(self) -> None:
        """Drop memoized estimates. Staleness contract: an estimate is a
        point-in-time answer memoized per unique request profile until the
        owner observes member state change (cluster status heartbeat /
        snapshot swap) and invalidates — the informer-cache granularity the
        reference's general estimator gets for free, applied to the gRPC
        accurate path. Without invalidation a long steady storm re-uses
        the first pass's fan-out; after it, the next pass re-queries every
        cluster live."""
        self._memo.clear()

    def make_batch_estimator(
        self,
        cluster_names: Sequence[str],
        *,
        max_workers: int = 64,
        timeout_seconds: Optional[float] = None,
    ):
        """Adapter for TensorScheduler.extra_estimators: returns
        fn(requests[B,R], replicas[B]) -> int32[B,C] with -1 where no
        estimator serves the cluster.

        Fan-out is CONCURRENT under one shared deadline
        (client/accurate.go:139-162): each cluster's per-profile queries
        run on a worker pool; a cluster missing the deadline answers
        UnauthenticReplica (-1) for this pass, so the min-merge ignores it
        instead of blocking scheduling — its late result is discarded,
        never applied to a later pass."""
        from concurrent.futures import ThreadPoolExecutor
        from concurrent.futures import wait as _fwait
        import time as _time

        names = list(cluster_names)
        # memo keys carry the closure's name tuple: memoized columns are
        # POSITIONAL over this estimator's name list, so two coexisting
        # batch estimators with different orderings (or subsets) of the
        # same registry must never read each other's columns
        memo_ns = tuple(names)

        def estimate(requests: np.ndarray, replicas: np.ndarray) -> np.ndarray:
            reqs = np.asarray(requests)
            b = len(reqs)
            out = np.full((b, len(names)), UNAUTHENTIC, np.int32)
            # intern the batch to unique profiles; answer memo hits without
            # touching the wire, fan out the misses concurrently
            uniq, inv = np.unique(reqs, axis=0, return_inverse=True)
            cols = [self._memo.get((memo_ns, row.tobytes())) for row in uniq]
            miss = [u for u, col in enumerate(cols) if col is None]
            if miss:
                if self._pool is None:
                    self._pool = ThreadPoolExecutor(max_workers)
                t0 = _time.perf_counter()
                miss_reqs = uniq[miss]
                futs = {}
                # clusters with no registered estimator answer -1
                # STRUCTURALLY (deterministic) and don't block memoization;
                # a TIMED-OUT or errored cluster answers -1 for this pass
                # only — memoizing a transient failure would pin the
                # snapshot-only fallback until the next invalidation
                complete = True
                for ci, name in enumerate(names):
                    est = self._by_cluster.get(name)
                    if est is None:
                        continue
                    futs[
                        self._pool.submit(
                            est.max_available_replicas, None, miss_reqs
                        )
                    ] = ci
                done, not_done = _fwait(futs, timeout=timeout_seconds)
                fresh = np.full(
                    (len(miss), len(names)), UNAUTHENTIC, np.int32
                )
                for f in done:
                    try:
                        vals = np.asarray(f.result(), np.int32)
                        fresh[:, futs[f]] = vals
                        if (vals < 0).any():
                            # the remote adapter reports its own per-RPC
                            # wire failures as -1 rows — same transient
                            complete = False
                    except Exception:  # noqa: BLE001 — wire failure = -1
                        complete = False
                for f in not_done:
                    f.cancel()
                    complete = False
                for k, u in enumerate(miss):
                    col = fresh[k]
                    cols[u] = col
                    if complete:
                        self._memo[(memo_ns, uniq[u].tobytes())] = col
                self.fanout_seconds_total += _time.perf_counter() - t0
            table = np.stack(cols)  # [U, C]
            out[:] = table[inv]
            return out

        return estimate
