from . import estimator_batch_pb2, estimator_pb2  # noqa: F401
