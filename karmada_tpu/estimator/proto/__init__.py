from . import estimator_pb2  # noqa: F401
