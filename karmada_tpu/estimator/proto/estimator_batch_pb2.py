"""Messages for estimator_batch.proto, built without protoc.

grpc_tools/protoc are not in the image (see estimator.proto's regen note),
and unlike the seed messages these did not ship with a pre-generated
module, so the FileDescriptorProto is constructed programmatically and
registered in the default pool — byte-for-byte the wire format protoc
would emit for karmada_tpu/estimator/proto/estimator_batch.proto, which
remains the human-readable contract. KEEP THE TWO IN SYNC.
"""

from __future__ import annotations

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

_PKG = "karmada_tpu.estimator"
_FILE = "karmada_tpu/estimator/proto/estimator_batch.proto"

_F = descriptor_pb2.FieldDescriptorProto


def _message(fdp, name: str, *fields):
    msg = fdp.message_type.add()
    msg.name = name
    for number, fname, ftype, repeated in fields:
        f = msg.field.add()
        f.name = fname
        f.number = number
        f.label = _F.LABEL_REPEATED if repeated else _F.LABEL_OPTIONAL
        if isinstance(ftype, str):  # message-typed field
            f.type = _F.TYPE_MESSAGE
            f.type_name = f".{_PKG}.{ftype}"
        else:
            f.type = ftype
    return msg


def _build() -> "descriptor_pool.DescriptorPool":
    pool = descriptor_pool.Default()
    try:  # already registered (re-import through a second path)
        pool.FindFileByName(_FILE)
        return pool
    except KeyError:
        pass
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = _FILE
    fdp.package = _PKG
    fdp.syntax = "proto3"
    _message(fdp, "Int64Row", (1, "values", _F.TYPE_INT64, True))
    _message(
        fdp, "MaxAvailableReplicasBatchRequest",
        (1, "clusters", _F.TYPE_STRING, True),
        (2, "dims", _F.TYPE_STRING, True),
        (3, "rows", "Int64Row", True),
        # one namespace per row (quota-plugin parity with the unary
        # path); proto3 repeated fields are backward/forward compatible —
        # empty on old clients, ignored by old servers
        (4, "namespaces", _F.TYPE_STRING, True),
    )
    _message(
        fdp, "ClusterBatchResult",
        (1, "cluster", _F.TYPE_STRING, False),
        (2, "max_replicas", _F.TYPE_INT32, True),
        (3, "generation", _F.TYPE_INT64, False),
    )
    _message(
        fdp, "MaxAvailableReplicasBatchResponse",
        (1, "results", "ClusterBatchResult", True),
    )
    _message(
        fdp, "GetGenerationsRequest",
        (1, "clusters", _F.TYPE_STRING, True),
    )
    _message(
        fdp, "GenerationEntry",
        (1, "cluster", _F.TYPE_STRING, False),
        (2, "generation", _F.TYPE_INT64, False),
    )
    _message(
        fdp, "GetGenerationsResponse",
        (1, "generations", "GenerationEntry", True),
    )
    pool.Add(fdp)
    return pool


def _cls(pool, name: str):
    return message_factory.GetMessageClass(
        pool.FindMessageTypeByName(f"{_PKG}.{name}")
    )


_pool = _build()

Int64Row = _cls(_pool, "Int64Row")
MaxAvailableReplicasBatchRequest = _cls(
    _pool, "MaxAvailableReplicasBatchRequest"
)
ClusterBatchResult = _cls(_pool, "ClusterBatchResult")
MaxAvailableReplicasBatchResponse = _cls(
    _pool, "MaxAvailableReplicasBatchResponse"
)
GetGenerationsRequest = _cls(_pool, "GetGenerationsRequest")
GenerationEntry = _cls(_pool, "GenerationEntry")
GetGenerationsResponse = _cls(_pool, "GetGenerationsResponse")
