"""Capacity estimators (ref: pkg/estimator)."""

from .accurate import (  # noqa: F401
    AccurateEstimator,
    EstimatorRegistry,
    NodeCache,
    NodeSnapshot,
    NodeState,
)
