"""Spawn a multiplexed estimator-server fleet as real OS processes.

One helper shared by the bench's live-estimator tier and the e2e tests
(duplicating the bring-up drifted once already): shard the cluster list
over N server processes (``python -m karmada_tpu.estimator --spec-file``,
MultiClusterEstimatorService routing by request.cluster), connect one gRPC
channel per server, and register a RemoteAccurateEstimator per cluster.
Ref: cmd/scheduler-estimator (per-member deployment), client/service.go
(discovery); the consolidated N-clusters-per-process shape is the
operator's answer at hundreds of members.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from dataclasses import dataclass, field


@dataclass
class EstimatorFleet:
    """Handles for a spawned estimator-server fleet; ``close()`` tears
    everything down (kill + wait + unlink)."""

    registry: object = None
    procs: list = field(default_factory=list)
    conns: list = field(default_factory=list)
    spec_paths: list = field(default_factory=list)

    def close(self) -> None:
        for conn in self.conns:
            try:
                conn.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for proc in self.procs:
            if proc.poll() is None:
                proc.kill()
        for proc in self.procs:
            try:
                proc.wait(timeout=5)
            except Exception:  # noqa: BLE001
                pass
        for path in self.spec_paths:
            try:
                os.unlink(path)
            except OSError:
                pass

    def __enter__(self) -> "EstimatorFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def spawn_estimator_fleet(
    names: list,
    free_caps,
    dims: list,
    *,
    n_servers: int = 2,
    index=None,
    timeout_seconds: float = 10.0,
) -> EstimatorFleet:
    """Spawn ``n_servers`` estimator processes hosting ``names`` between
    them, each cluster's single node holding the ``free_caps`` row for it
    (capacities keyed positionally via ``index`` — a name->row mapping —
    or by list order). Returns an EstimatorFleet whose ``registry`` holds
    a RemoteAccurateEstimator per cluster."""
    from ..localup import scrape_line, spawn_child
    from .accurate import EstimatorRegistry
    from .grpc_transport import GrpcEstimatorConnection, RemoteAccurateEstimator

    fleet = EstimatorFleet(registry=EstimatorRegistry())
    try:
        if index is None:
            # one name->row map up front: names.index(name) inside the
            # spec comprehension is O(n) per lookup — an O(n^2 x dims)
            # spec build at 512+ clusters
            index = {name: i for i, name in enumerate(names)}
        shard = (len(names) + n_servers - 1) // n_servers
        for s in range(n_servers):
            names_s = names[s * shard:(s + 1) * shard]
            if not names_s:
                continue
            spec = {
                name: {
                    d: int(free_caps[index[name]][r])
                    for r, d in enumerate(dims)
                }
                for name in names_s
            }
            f = tempfile.NamedTemporaryFile("w", suffix=".json", delete=False)
            json.dump(spec, f)
            f.close()
            fleet.spec_paths.append(f.name)
            proc = spawn_child(
                [sys.executable, "-m", "karmada_tpu.estimator",
                 "--spec-file", f.name]
            )
            fleet.procs.append(proc)
            port = scrape_line(proc, r"port (\d+)", timeout=120)
            conn = GrpcEstimatorConnection(
                "multi", f"127.0.0.1:{port}",
                timeout_seconds=timeout_seconds,
            )
            fleet.conns.append(conn)
            for name in names_s:
                fleet.registry.register(
                    RemoteAccurateEstimator(name, conn, lambda: list(dims))
                )
        return fleet
    except Exception:
        fleet.close()
        raise
