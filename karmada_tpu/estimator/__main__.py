"""Estimator server process: ``python -m karmada_tpu.estimator``.

Ref: cmd/scheduler-estimator — one estimator deployment per member cluster,
serving MaxAvailableReplicas / GetUnschedulableReplicas over gRPC from the
member's node/pod state. In this simulated world the member's nodes are
synthesized in-process (the node-informer stand-in); the wire contract and
the scheduler-side fan-out are the real thing.
"""

from __future__ import annotations

import argparse
import sys

from .accurate import AccurateEstimator, NodeCache, NodeState
from .grpc_transport import EstimatorGrpcServer
from .service import EstimatorService

DIMS = ["cpu", "memory", "pods", "ephemeral-storage"]


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="karmada-tpu estimator server")
    p.add_argument("--cluster", default="")
    p.add_argument("--address", default="127.0.0.1:0")
    p.add_argument("--nodes", type=int, default=3)
    p.add_argument("--cpu", type=int, default=16000, help="milli-cpu per node")
    p.add_argument("--memory", type=int, default=64 << 30)
    p.add_argument("--pods", type=int, default=110)
    p.add_argument(
        "--spec-file", default="",
        help="JSON {cluster: {dim: capacity}} — host MANY clusters' "
        "estimators in THIS process (MultiClusterEstimatorService routes "
        "by request.cluster; the consolidated deployment shape for "
        "hundreds of members). Each cluster gets one node whose "
        "allocatable IS the given free capacity.",
    )
    p.add_argument(
        "--metrics-port", default=None,
        help="serve /metrics + /healthz + /debug/traces on this port or HOST:PORT "
        "(0 = ephemeral, printed as 'metrics listening on port N'; "
        "default: $KARMADA_TPU_METRICS_PORT, empty = disabled)",
    )
    args = p.parse_args(argv)
    # chaos: arm deterministic fault injection from the environment
    # (KARMADA_TPU_FAULT_SPEC; disarmed when empty — zero overhead)
    from ..utils.faultinject import arm_from_env
    from ..utils.tracing import register_peers_from_env, tracer

    arm_from_env()
    # cross-process tracing: handler spans export as proc="estimator"
    tracer.set_process("estimator")
    register_peers_from_env()
    if bool(args.cluster) == bool(args.spec_file):
        p.error("exactly one of --cluster / --spec-file is required")

    if args.spec_file:
        import json

        from .service import MultiClusterEstimatorService

        with open(args.spec_file) as f:
            spec: dict = json.load(f)
        dims = sorted({d for caps in spec.values() for d in caps})
        # NodeCache (not NodeSnapshot): the long-lived server's snapshot
        # generation stays pinned between member events, so the scheduler
        # side's GetGenerations ping can prove "nothing moved" and skip the
        # profile fan-out entirely (the generation-gated refresh contract)
        services = {
            name: EstimatorService(
                AccurateEstimator(
                    name,
                    NodeCache(
                        dims,
                        [NodeState(name=f"{name}-node-0",
                                   allocatable=dict(caps))],
                    ),
                )
            )
            for name, caps in spec.items()
        }
        server = EstimatorGrpcServer(
            MultiClusterEstimatorService(services), args.address,
            max_workers=32,
        )
        port = server.start()
        print(
            f"estimator multi ({len(services)} clusters) listening on "
            f"port {port}",
            flush=True,
        )
    else:
        nodes = [
            NodeState(
                name=f"{args.cluster}-node-{i}",
                allocatable={
                    "cpu": args.cpu,
                    "memory": args.memory,
                    "pods": args.pods,
                    "ephemeral-storage": 100 << 30,
                },
            )
            for i in range(args.nodes)
        ]
        est = AccurateEstimator(args.cluster, NodeCache(DIMS, nodes))
        server = EstimatorGrpcServer(EstimatorService(est), args.address)
        port = server.start()
        # the parent process scrapes this line to learn the bound port
        print(f"estimator {args.cluster} listening on port {port}", flush=True)

    from ..utils.metrics import serve_process_metrics

    # AFTER the gRPC port line: orchestrators scrape the FIRST
    # "port (\\d+)" match, which must stay the serving port
    metrics = serve_process_metrics(args.metrics_port)
    if metrics is not None:
        print(f"metrics listening on port {metrics.port}", flush=True)
    try:
        server._server.wait_for_termination()
    except KeyboardInterrupt:
        pass
    finally:
        if metrics is not None:
            metrics.stop()
        server.stop()


if __name__ == "__main__":
    main()
