"""Estimator server process: ``python -m karmada_tpu.estimator``.

Ref: cmd/scheduler-estimator — one estimator deployment per member cluster,
serving MaxAvailableReplicas / GetUnschedulableReplicas over gRPC from the
member's node/pod state. In this simulated world the member's nodes are
synthesized in-process (the node-informer stand-in); the wire contract and
the scheduler-side fan-out are the real thing.
"""

from __future__ import annotations

import argparse
import sys

from .accurate import AccurateEstimator, NodeSnapshot, NodeState
from .grpc_transport import EstimatorGrpcServer
from .service import EstimatorService

DIMS = ["cpu", "memory", "pods", "ephemeral-storage"]


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="karmada-tpu estimator server")
    p.add_argument("--cluster", required=True)
    p.add_argument("--address", default="127.0.0.1:0")
    p.add_argument("--nodes", type=int, default=3)
    p.add_argument("--cpu", type=int, default=16000, help="milli-cpu per node")
    p.add_argument("--memory", type=int, default=64 << 30)
    p.add_argument("--pods", type=int, default=110)
    args = p.parse_args(argv)

    nodes = [
        NodeState(
            name=f"{args.cluster}-node-{i}",
            allocatable={
                "cpu": args.cpu,
                "memory": args.memory,
                "pods": args.pods,
                "ephemeral-storage": 100 << 30,
            },
        )
        for i in range(args.nodes)
    ]
    est = AccurateEstimator(args.cluster, NodeSnapshot(nodes, DIMS))
    server = EstimatorGrpcServer(EstimatorService(est), args.address)
    port = server.start()
    # the parent process scrapes this line to learn the bound port
    print(f"estimator {args.cluster} listening on port {port}", flush=True)
    try:
        server._server.wait_for_termination()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()


if __name__ == "__main__":
    main()
