"""Real gRPC transport for the estimator channel (DCN side).

Ref: pkg/estimator/server/server.go:171-173 (mTLS gRPC serve),
pkg/util/grpcconnection/config.go (client/server TLS config: server cert +
key, optional client-auth CA; insecure fallback), client/cache.go (per-
cluster connection cache) and client/service.go (discovery by naming
convention ``{prefix}-{cluster}:port``).

grpc_tools (python codegen plugin) is not in the image, so the servicer and
stub are wired by hand over the protoc-generated ``estimator_pb2`` messages
using grpc's generic handler API — same wire format a generated stub would
speak. The connection object satisfies the ``call(method, request)`` seam of
``EstimatorClientPool``, so the scheduler side is transport-agnostic: swap
the resolver and the same fan-out runs in-proc or over the network.
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from .proto import estimator_batch_pb2 as bpb
from .proto import estimator_pb2 as pb
from .service import (
    ClusterBatchResult,
    EstimatorService,
    GetGenerationsRequest,
    GetGenerationsResponse,
    MaxAvailableReplicasBatchRequest,
    MaxAvailableReplicasBatchResponse,
    MaxAvailableReplicasRequest,
    MaxAvailableReplicasResponse,
    UnschedulableReplicasRequest,
    UnschedulableReplicasResponse,
    UnsupportedMethodError,
)

SERVICE_NAME = "karmada_tpu.estimator.Estimator"


def _req_to_pb(req: MaxAvailableReplicasRequest) -> pb.MaxAvailableReplicasRequest:
    msg = pb.MaxAvailableReplicasRequest(cluster=req.cluster)
    rr = msg.replica_requirements
    for k, v in req.resource_request.items():
        rr.resource_request[k] = int(v)
    rr.namespace = req.namespace
    rr.priority_class_name = req.priority_class_name
    for k, v in req.node_selector.items():
        rr.node_claim.node_selector[k] = v
    for t in req.tolerations:
        tol = rr.node_claim.tolerations.add()
        tol.key = t.get("key", "")
        tol.operator = t.get("operator", "Equal")
        tol.value = t.get("value", "")
        tol.effect = t.get("effect", "")
        secs = t.get("toleration_seconds")
        if secs is not None:
            tol.toleration_seconds = int(secs)
            tol.has_toleration_seconds = True
    return msg


def _pb_to_req(msg: pb.MaxAvailableReplicasRequest) -> MaxAvailableReplicasRequest:
    rr = msg.replica_requirements
    tolerations = []
    for tol in rr.node_claim.tolerations:
        d = {
            "key": tol.key,
            "operator": tol.operator or "Equal",
            "value": tol.value,
            "effect": tol.effect,
        }
        if tol.has_toleration_seconds:
            d["toleration_seconds"] = tol.toleration_seconds
        tolerations.append(d)
    return MaxAvailableReplicasRequest(
        cluster=msg.cluster,
        resource_request=dict(rr.resource_request),
        node_selector=dict(rr.node_claim.node_selector),
        tolerations=tolerations,
        namespace=rr.namespace,
        priority_class_name=rr.priority_class_name,
    )


def _unsched_to_pb(req: UnschedulableReplicasRequest) -> pb.UnschedulableReplicasRequest:
    return pb.UnschedulableReplicasRequest(
        cluster=req.cluster,
        resource_kind=req.resource_kind,
        namespace=req.namespace,
        name=req.name,
        unschedulable_threshold_seconds=req.unschedulable_threshold_seconds,
    )


def _pb_to_unsched(msg: pb.UnschedulableReplicasRequest) -> UnschedulableReplicasRequest:
    return UnschedulableReplicasRequest(
        cluster=msg.cluster,
        resource_kind=msg.resource_kind,
        namespace=msg.namespace,
        name=msg.name,
        unschedulable_threshold_seconds=msg.unschedulable_threshold_seconds,
    )


def _batch_to_pb(
    req: MaxAvailableReplicasBatchRequest,
) -> "bpb.MaxAvailableReplicasBatchRequest":
    msg = bpb.MaxAvailableReplicasBatchRequest(
        clusters=list(req.clusters), dims=list(req.dims),
        namespaces=list(getattr(req, "namespaces", []) or []),
    )
    for row in req.rows:
        msg.rows.add().values.extend(int(v) for v in row)
    return msg


def _pb_to_batch(
    msg: "bpb.MaxAvailableReplicasBatchRequest",
) -> MaxAvailableReplicasBatchRequest:
    return MaxAvailableReplicasBatchRequest(
        clusters=list(msg.clusters),
        dims=list(msg.dims),
        rows=[list(row.values) for row in msg.rows],
        namespaces=list(msg.namespaces),
    )


def _batch_resp_to_pb(
    resp: MaxAvailableReplicasBatchResponse,
) -> "bpb.MaxAvailableReplicasBatchResponse":
    msg = bpb.MaxAvailableReplicasBatchResponse()
    for res in resp.results:
        out = msg.results.add()
        out.cluster = res.cluster
        out.max_replicas.extend(int(v) for v in res.max_replicas)
        out.generation = int(res.generation)
    return msg


def _pb_to_batch_resp(
    msg: "bpb.MaxAvailableReplicasBatchResponse",
) -> MaxAvailableReplicasBatchResponse:
    return MaxAvailableReplicasBatchResponse(
        results=[
            ClusterBatchResult(
                cluster=res.cluster,
                max_replicas=list(res.max_replicas),
                generation=res.generation,
            )
            for res in msg.results
        ]
    )


def _gens_to_pb(req: GetGenerationsRequest) -> "bpb.GetGenerationsRequest":
    return bpb.GetGenerationsRequest(clusters=list(req.clusters))


def _pb_to_gens(msg: "bpb.GetGenerationsRequest") -> GetGenerationsRequest:
    return GetGenerationsRequest(clusters=list(msg.clusters))


def _gens_resp_to_pb(
    resp: GetGenerationsResponse,
) -> "bpb.GetGenerationsResponse":
    msg = bpb.GetGenerationsResponse()
    for cluster, gen in resp.generations.items():
        entry = msg.generations.add()
        entry.cluster = cluster
        entry.generation = int(gen)
    return msg


def _pb_to_gens_resp(
    msg: "bpb.GetGenerationsResponse",
) -> GetGenerationsResponse:
    return GetGenerationsResponse(
        generations={e.cluster: e.generation for e in msg.generations}
    )


class EstimatorGrpcServer:
    """Serves one cluster's ``EstimatorService`` over gRPC, optionally mTLS
    (ref: server/server.go:171-173; grpcconnection/config.go ServerConfig)."""

    def __init__(
        self,
        service: EstimatorService,
        address: str = "127.0.0.1:0",
        *,
        server_cert: Optional[bytes] = None,
        server_key: Optional[bytes] = None,
        client_ca: Optional[bytes] = None,
        max_workers: int = 8,
        enable_batch: bool = True,
    ):
        self._service = service
        # SO_REUSEPORT off: a port conflict must surface at bind time, not
        # silently load-balance two estimator servers on one port
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[("grpc.so_reuseport", 0)],
        )

        # served-RPC accounting at the wire choke point (covers the single-
        # and multi-cluster services alike): the estimator PROCESS's
        # /metrics answers with this family (ISSUE 6 c). Each handler
        # records one ``estimator.serve`` span under the CALLER's wave —
        # the trace context rides the invocation metadata (ISSUE 10)
        from ..utils.metrics import estimator_server_requests
        from ..utils.tracing import decode_trace_metadata, tracer

        def _ctx(context):
            return decode_trace_metadata(context.invocation_metadata())

        def max_available(request: pb.MaxAvailableReplicasRequest, context):
            estimator_server_requests.inc(method="MaxAvailableReplicas")
            with tracer.server_span(
                "estimator.serve", _ctx(context),
                method="MaxAvailableReplicas",
            ):
                resp = self._service.max_available_replicas(
                    _pb_to_req(request)
                )
            return pb.MaxAvailableReplicasResponse(max_replicas=resp.max_replicas)

        def unschedulable(request: pb.UnschedulableReplicasRequest, context):
            estimator_server_requests.inc(method="GetUnschedulableReplicas")
            with tracer.server_span(
                "estimator.serve", _ctx(context),
                method="GetUnschedulableReplicas",
            ):
                resp = self._service.get_unschedulable_replicas(
                    _pb_to_unsched(request)
                )
            return pb.UnschedulableReplicasResponse(
                unschedulable_replicas=resp.unschedulable_replicas
            )

        def max_available_batch(
            request: "bpb.MaxAvailableReplicasBatchRequest", context
        ):
            estimator_server_requests.inc(method="MaxAvailableReplicasBatch")
            with tracer.server_span(
                "estimator.serve", _ctx(context),
                method="MaxAvailableReplicasBatch",
            ) as sp:
                sp.attrs["rows"] = len(request.rows)
                resp = self._service.max_available_replicas_batch(
                    _pb_to_batch(request)
                )
            return _batch_resp_to_pb(resp)

        def get_generations(request: "bpb.GetGenerationsRequest", context):
            estimator_server_requests.inc(method="GetGenerations")
            with tracer.server_span(
                "estimator.serve", _ctx(context), method="GetGenerations",
            ):
                return _gens_resp_to_pb(
                    self._service.get_generations(_pb_to_gens(request))
                )

        handlers = {
            "MaxAvailableReplicas": grpc.unary_unary_rpc_method_handler(
                max_available,
                request_deserializer=pb.MaxAvailableReplicasRequest.FromString,
                response_serializer=pb.MaxAvailableReplicasResponse.SerializeToString,
            ),
            "GetUnschedulableReplicas": grpc.unary_unary_rpc_method_handler(
                unschedulable,
                request_deserializer=pb.UnschedulableReplicasRequest.FromString,
                response_serializer=pb.UnschedulableReplicasResponse.SerializeToString,
            ),
        }
        # the batched protocol + generation pings ship together; a service
        # object without the methods (or enable_batch=False — the old-server
        # shape, used by the mixed-version tests) leaves them unregistered
        # so clients get UNIMPLEMENTED and negotiate the unary fallback
        if enable_batch and hasattr(service, "max_available_replicas_batch"):
            handlers["MaxAvailableReplicasBatch"] = (
                grpc.unary_unary_rpc_method_handler(
                    max_available_batch,
                    request_deserializer=(
                        bpb.MaxAvailableReplicasBatchRequest.FromString
                    ),
                    response_serializer=(
                        bpb.MaxAvailableReplicasBatchResponse.SerializeToString
                    ),
                )
            )
            handlers["GetGenerations"] = grpc.unary_unary_rpc_method_handler(
                get_generations,
                request_deserializer=bpb.GetGenerationsRequest.FromString,
                response_serializer=(
                    bpb.GetGenerationsResponse.SerializeToString
                ),
            )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
        )
        if bool(server_cert) != bool(server_key) or (
            client_ca and not (server_cert and server_key)
        ):
            # incomplete TLS material must fail loudly, never silently
            # degrade to plaintext (grpcconnection/config.go errors likewise)
            raise ValueError(
                "incomplete server TLS config: server_cert and server_key are "
                "both required (and client_ca implies them)"
            )
        if server_cert and server_key:
            creds = grpc.ssl_server_credentials(
                [(server_key, server_cert)],
                root_certificates=client_ca,
                require_client_auth=client_ca is not None,
            )
            self.port = self._server.add_secure_port(address, creds)
        else:
            self.port = self._server.add_insecure_port(address)
        if self.port == 0:
            raise RuntimeError(f"estimator gRPC server failed to bind {address}")

    def start(self) -> int:
        self._server.start()
        return self.port

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self._server.stop(grace)


class GrpcEstimatorConnection:
    """Client side of one cluster's estimator channel. Satisfies the
    ``call(method, request)`` seam of ``EstimatorClientPool`` (ref:
    client/cache.go EstimatorClient wrapper)."""

    def __init__(
        self,
        cluster: str,
        target: str,
        *,
        root_ca: Optional[bytes] = None,
        client_cert: Optional[bytes] = None,
        client_key: Optional[bytes] = None,
        timeout_seconds: float = 3.0,
    ):
        self.cluster = cluster
        self.target = target
        self.timeout = timeout_seconds
        if (client_cert or client_key) and not (root_ca and client_cert and client_key):
            raise ValueError(
                "incomplete client TLS config: client_cert/client_key require "
                "each other and root_ca"
            )
        if root_ca is not None:
            creds = grpc.ssl_channel_credentials(
                root_certificates=root_ca,
                private_key=client_key,
                certificate_chain=client_cert,
            )
            self._channel = grpc.secure_channel(target, creds)
        else:
            self._channel = grpc.insecure_channel(target)
        self._max_available = self._channel.unary_unary(
            f"/{SERVICE_NAME}/MaxAvailableReplicas",
            request_serializer=pb.MaxAvailableReplicasRequest.SerializeToString,
            response_deserializer=pb.MaxAvailableReplicasResponse.FromString,
        )
        self._unschedulable = self._channel.unary_unary(
            f"/{SERVICE_NAME}/GetUnschedulableReplicas",
            request_serializer=pb.UnschedulableReplicasRequest.SerializeToString,
            response_deserializer=pb.UnschedulableReplicasResponse.FromString,
        )
        self._batch = self._channel.unary_unary(
            f"/{SERVICE_NAME}/MaxAvailableReplicasBatch",
            request_serializer=(
                bpb.MaxAvailableReplicasBatchRequest.SerializeToString
            ),
            response_deserializer=(
                bpb.MaxAvailableReplicasBatchResponse.FromString
            ),
        )
        self._generations = self._channel.unary_unary(
            f"/{SERVICE_NAME}/GetGenerations",
            request_serializer=bpb.GetGenerationsRequest.SerializeToString,
            response_deserializer=bpb.GetGenerationsResponse.FromString,
        )
        # batched-protocol negotiation: None until the first batch/ping
        # call, then pinned until the channel proves unhealthy — a WIRE
        # failure resets it to None so the transparently-reconnected
        # channel re-probes before reuse (the returning server may be a
        # different build), and an evicted connection is rebuilt from the
        # resolver with the same effect
        self.supports_batch: Optional[bool] = None
        # unified channel resilience (utils.backoff): consecutive wire
        # failures open the breaker; the registry's fan-out consults
        # ``breaker.engaged()`` BEFORE submitting, so a dead server
        # answers UnauthenticReplica immediately instead of burning the
        # executor (and the pass deadline) on a doomed RPC
        from ..utils.backoff import default_breaker

        self.breaker = default_breaker(f"estimator@{target}")

    def _unimplemented(self, method: str, exc) -> UnsupportedMethodError:
        # UNIMPLEMENTED = an old server build without the batched protocol:
        # remember the negotiation on THIS connection and let the caller
        # fall back to per-profile unary (any other failure propagates)
        self.supports_batch = False
        return UnsupportedMethodError(method)

    def call(self, method: str, request):
        from ..utils.backoff import CircuitBreakerOpen
        from ..utils.faultinject import apply_fault, fault_point
        from ..utils.tracing import trace_metadata, tracer

        if not self.breaker.allow():
            raise CircuitBreakerOpen(
                f"estimator {self.target} breaker is open"
            )
        ok = False
        try:
            # ONE client span per wire attempt (a caller's retry opens a
            # fresh span, so each server-side span re-parents under
            # exactly one client span); the context is captured INSIDE
            # the span so the server records under this span's id
            with tracer.span(
                "estimator.rpc", remote=True, peer=self.target,
                cluster=self.cluster, method=method,
            ):
                md = trace_metadata(tracer.current_context())
                apply_fault(
                    fault_point("estimator.rpc", f"{method}:{self.cluster}"),
                    "estimator.rpc", f"{method}:{self.cluster}",
                    channel=self._channel,
                )
                resp = self._call(method, request, md)
            ok = True
            return resp
        except UnsupportedMethodError:
            # the server ANSWERED (an old build negotiating the fallback):
            # the channel itself is healthy
            ok = True
            raise
        except grpc.RpcError:
            # a wire failure invalidates the pinned batch negotiation —
            # the channel reconnects transparently underneath, and the
            # server that comes back may be a different build, so the
            # next batch/ping call must RE-PROBE instead of trusting a
            # dead server's answer
            self.supports_batch = None
            raise
        finally:
            (self.breaker.record_success if ok
             else self.breaker.record_failure)()

    def _call(self, method: str, request, metadata=()):
        if method == "MaxAvailableReplicas":
            resp = self._max_available(
                _req_to_pb(request), timeout=self.timeout, metadata=metadata
            )
            return MaxAvailableReplicasResponse(max_replicas=resp.max_replicas)
        if method == "GetUnschedulableReplicas":
            resp = self._unschedulable(
                _unsched_to_pb(request), timeout=self.timeout,
                metadata=metadata,
            )
            return UnschedulableReplicasResponse(
                unschedulable_replicas=resp.unschedulable_replicas
            )
        if method == "MaxAvailableReplicasBatch":
            try:
                resp = self._batch(
                    _batch_to_pb(request), timeout=self.timeout,
                    metadata=metadata,
                )
            except grpc.RpcError as exc:
                if exc.code() == grpc.StatusCode.UNIMPLEMENTED:
                    raise self._unimplemented(method, exc) from exc
                raise
            self.supports_batch = True
            return _pb_to_batch_resp(resp)
        if method == "GetGenerations":
            try:
                resp = self._generations(
                    _gens_to_pb(request), timeout=self.timeout,
                    metadata=metadata,
                )
            except grpc.RpcError as exc:
                if exc.code() == grpc.StatusCode.UNIMPLEMENTED:
                    raise self._unimplemented(method, exc) from exc
                raise
            self.supports_batch = True
            return _pb_to_gens_resp(resp)
        raise ValueError(f"unknown method {method}")

    def call_future(self, method: str, request):
        """Pipelined seam for the unary fallback: returns a grpc future so
        a client can keep N per-profile calls in flight on one channel
        instead of blocking sequentially. Resolve with ``future.result()``;
        the response is the raw pb message (use ``.max_replicas``)."""
        if method == "MaxAvailableReplicas":
            from ..utils.backoff import CircuitBreakerOpen
            from ..utils.faultinject import apply_fault, fault_point
            from ..utils.tracing import TraceContext, trace_metadata, tracer

            # non-consuming breaker gate (engaged(), not allow()): futures
            # resolve off-thread, so outcomes feed the breaker via a done
            # callback rather than the probe-slot protocol
            if self.breaker.engaged():
                raise CircuitBreakerOpen(
                    f"estimator {self.target} breaker is open"
                )
            # the in-flight window closes from the grpc done callback (on
            # another thread), so the client span is MANUAL — and the
            # propagated context names the manual span itself, so the
            # server span re-parents under the attempt that carried it
            sp = tracer.open_manual(
                "estimator.rpc", remote=True, peer=self.target,
                cluster=self.cluster, method=method,
            )
            md = trace_metadata(TraceContext(
                wave=sp.wave, trace_id=sp.trace_id, span_id=sp.span_id,
                proc=tracer.proc,
            ))
            try:
                apply_fault(
                    fault_point(
                        "estimator.rpc", f"{method}:{self.cluster}:future"
                    ),
                    "estimator.rpc", f"{method}:{self.cluster}",
                    channel=self._channel,
                )
                fut = self._max_available.future(
                    _req_to_pb(request), timeout=self.timeout, metadata=md
                )
            except BaseException:
                tracer.close_manual(sp)
                raise
            fut.add_done_callback(
                lambda f: (
                    tracer.close_manual(sp),
                    (
                        self.breaker.record_failure()
                        if (not f.cancelled() and f.exception() is not None)
                        else self.breaker.record_success()
                    ),
                )
            )
            return fut
        raise ValueError(f"no future seam for method {method}")

    def close(self) -> None:
        self._channel.close()


def conventional_target(prefix: str, cluster: str, port: int, host: str = "") -> str:
    """Discovery by naming convention (ref: client/service.go —
    ``{prefix}-{cluster}.{ns}:port``; here host defaults to the name itself
    so DNS or /etc/hosts resolves it, tests pass an explicit host)."""
    name = f"{prefix}-{cluster}"
    return f"{host or name}:{port}"


class RemoteAccurateEstimator:
    """EstimatorRegistry-compatible adapter over a gRPC connection: the
    scheduler-side face of an estimator SERVER running in another process
    (per-member deployment; ref client/accurate.go SchedulerEstimator).

    ``max_available_replicas`` interns the request batch to its unique
    profiles and issues ONE MaxAvailableReplicasBatch RPC carrying the
    whole matrix — the reference queries per binding; one batched call is
    the same answer at orders fewer round-trips. Old servers answer
    UNIMPLEMENTED and the connection negotiates the per-profile unary
    fallback, PIPELINED over the channel (``call_future``) instead of
    blocking sequentially. Unreachable estimators answer -1
    (UnauthenticReplica, client/interface.go:30) so the min-merge ignores
    them instead of blocking scheduling."""

    def __init__(self, cluster_name: str, conn, dims_provider):
        import numpy as _np

        self.cluster_name = cluster_name
        self.conn = conn
        self.dims_provider = dims_provider  # () -> list[str] snapshot dims
        self.unschedulable: dict[str, int] = {}
        self._np = _np

    def query_profiles(self, dims, uniq):
        """int32[U] answers for unique profile rows over ``dims``, plus the
        server's snapshot generation (None when the fallback path answered
        — old servers have no generation to report)."""
        from .accurate import UNAUTHENTIC, conn_supports_batch

        np_ = self._np
        if conn_supports_batch(self.conn) is not False:
            try:
                resp = self.conn.call(
                    "MaxAvailableReplicasBatch",
                    MaxAvailableReplicasBatchRequest(
                        clusters=[self.cluster_name],
                        dims=list(dims),
                        rows=[[int(v) for v in row] for row in uniq],
                    ),
                )
                for res in resp.results:
                    if res.cluster == self.cluster_name:
                        return (
                            np_.asarray(res.max_replicas, np_.int32),
                            int(res.generation),
                        )
                # server answered but does not host this cluster
                return np_.full(len(uniq), UNAUTHENTIC, np_.int32), None
            except UnsupportedMethodError:
                pass  # negotiated on the conn: fall through to unary
            except Exception:  # noqa: BLE001 — wire failure = no answer
                return np_.full(len(uniq), UNAUTHENTIC, np_.int32), None
        return self._query_profiles_unary(dims, uniq), None

    def _query_profiles_unary(self, dims, uniq):
        """Per-profile unary fallback, pipelined: keep up to
        ``fallback_width()`` calls in flight on the channel. In-proc
        connections (no ``call_future`` seam) just loop — there is no wire
        latency to hide."""
        from .accurate import UNAUTHENTIC, fallback_width

        np_ = self._np
        out = np_.empty(len(uniq), np_.int32)
        reqs = [
            MaxAvailableReplicasRequest(
                cluster=self.cluster_name,
                resource_request={
                    d: int(q) for d, q in zip(dims, row) if q > 0
                },
            )
            for row in uniq
        ]
        submit = getattr(self.conn, "call_future", None)
        if submit is None:
            for u, req in enumerate(reqs):
                try:
                    resp = self.conn.call("MaxAvailableReplicas", req)
                    out[u] = resp.max_replicas
                except Exception:  # noqa: BLE001
                    out[u] = UNAUTHENTIC
            return out
        width = fallback_width()
        for start in range(0, len(reqs), width):
            window = []
            for u in range(start, min(start + width, len(reqs))):
                try:
                    window.append((u, submit("MaxAvailableReplicas", reqs[u])))
                except Exception:  # noqa: BLE001 — submit failure = -1
                    out[u] = UNAUTHENTIC
            for u, fut in window:
                try:
                    out[u] = fut.result().max_replicas
                except Exception:  # noqa: BLE001
                    out[u] = UNAUTHENTIC
        return out

    def max_available_replicas(self, requirements, requests_batch=None):
        np_ = self._np
        if requests_batch is None:
            req = dict(requirements.resource_request) if requirements else {}
            try:
                resp = self.conn.call(
                    "MaxAvailableReplicas",
                    MaxAvailableReplicasRequest(
                        cluster=self.cluster_name, resource_request=req
                    ),
                )
                return np_.asarray([resp.max_replicas], np_.int32)
            except Exception:  # noqa: BLE001 — wire failure = no answer
                return np_.asarray([-1], np_.int32)
        dims = list(self.dims_provider())
        batch = np_.asarray(requests_batch, np_.int64)
        uniq, inv = np_.unique(batch, axis=0, return_inverse=True)
        per_prof, _gen = self.query_profiles(dims, uniq)
        return per_prof[inv]

    def get_unschedulable_replicas(self, namespace: str, name: str) -> int:
        try:
            resp = self.conn.call(
                "GetUnschedulableReplicas",
                UnschedulableReplicasRequest(
                    cluster=self.cluster_name, namespace=namespace, name=name
                ),
            )
            return resp.unschedulable_replicas
        except Exception:  # noqa: BLE001
            return 0
