"""Estimator service contract + scheduler-side connection machinery.

Ref: pkg/estimator/service/service.proto:26-29 (service Estimator —
MaxAvailableReplicas / GetUnschedulableReplicas), pb/types.go:26-119
(request/response shapes), client/{cache,service}.go (per-cluster connection
cache, naming-convention discovery {prefix}-{cluster}:port) and
client/accurate.go:139-162 (concurrent fan-out under one deadline).

The wire types are dataclasses mirroring the protobuf schema. Transports
are pluggable behind the ``call(method, request)`` seam: the in-proc
transport calls the service object directly; the real gRPC/protobuf
transport (optionally mTLS) lives in :mod:`.grpc_transport` and drops into
the same pool via the resolver, so the scheduler side never knows which
wire it is on.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import asdict, dataclass, field
from typing import Callable, Optional

log = logging.getLogger("karmada_tpu")

import numpy as np

from ..api.work import ReplicaRequirements
from .accurate import UNAUTHENTIC, AccurateEstimator


@dataclass
class MaxAvailableReplicasRequest:
    cluster: str = ""
    # ReplicaRequirements (pb/types.go:52-69)
    resource_request: dict[str, int] = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[dict] = field(default_factory=list)
    namespace: str = ""
    priority_class_name: str = ""

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class MaxAvailableReplicasResponse:
    max_replicas: int = 0


@dataclass
class UnschedulableReplicasRequest:
    cluster: str = ""
    resource_kind: str = ""
    namespace: str = ""
    name: str = ""
    unschedulable_threshold_seconds: int = 60


@dataclass
class UnschedulableReplicasResponse:
    unschedulable_replicas: int = 0


# -- batched protocol + generation pings (estimator_batch.proto) ------------


@dataclass
class MaxAvailableReplicasBatchRequest:
    """One RPC per SERVER per pass: the whole unique-profile matrix for
    every cluster the server hosts (empty ``clusters`` = all hosted).
    ``rows`` are positional over ``dims``; the server projects them onto
    its own dim order by name. ``namespaces`` optionally carries one
    namespace per row so the server's ResourceQuota plugin caps each
    row's answer exactly like the unary path does (empty = no namespaces,
    the pre-quota wire shape — old clients keep working)."""

    clusters: list[str] = field(default_factory=list)
    dims: list[str] = field(default_factory=list)
    rows: list = field(default_factory=list)  # U x len(dims) ints
    namespaces: list[str] = field(default_factory=list)  # one per row


@dataclass
class ClusterBatchResult:
    cluster: str = ""
    max_replicas: list[int] = field(default_factory=list)  # one per row
    generation: int = 0  # snapshot generation the answers were computed at


@dataclass
class MaxAvailableReplicasBatchResponse:
    results: list[ClusterBatchResult] = field(default_factory=list)


@dataclass
class GetGenerationsRequest:
    clusters: list[str] = field(default_factory=list)  # empty = all hosted


@dataclass
class GetGenerationsResponse:
    generations: dict[str, int] = field(default_factory=dict)


class UnsupportedMethodError(RuntimeError):
    """The server does not speak this method (an old estimator build):
    gRPC UNIMPLEMENTED translated at the transport seam so in-proc and
    wire connections negotiate the fallback identically."""


class EstimatorService:
    """Server side: wraps one cluster's AccurateEstimator behind the service
    contract (ref: server/server.go:194-225)."""

    def __init__(self, estimator: AccurateEstimator):
        self.estimator = estimator

    def max_available_replicas(
        self, req: MaxAvailableReplicasRequest
    ) -> MaxAvailableReplicasResponse:
        requirements = ReplicaRequirements(
            resource_request=dict(req.resource_request),
            namespace=req.namespace,
            priority_class_name=req.priority_class_name,
        )
        if req.node_selector or req.tolerations:
            from ..api.work import NodeClaim

            requirements.node_claim = NodeClaim(
                node_selector=dict(req.node_selector),
                tolerations=list(req.tolerations),
            )
        dims = self.estimator.snapshot.dims
        row = np.zeros((1, len(dims)), np.int64)
        for j, d in enumerate(dims):
            row[0, j] = req.resource_request.get(d, 0)
        out = self.estimator.max_available_replicas(requirements, row)
        return MaxAvailableReplicasResponse(max_replicas=int(out[0]))

    def get_unschedulable_replicas(
        self, req: UnschedulableReplicasRequest
    ) -> UnschedulableReplicasResponse:
        key = f"{req.namespace}/{req.name}" if req.namespace else req.name
        return UnschedulableReplicasResponse(
            unschedulable_replicas=self.estimator.get_unschedulable_replicas(key)
        )

    def generation(self) -> int:
        """Monotonic snapshot generation: NodeCache bumps it on every
        upsert_node/add_pod/remove_* event; a static NodeSnapshot pins it
        (no events means the estimate can never go stale)."""
        return int(getattr(self.estimator.snapshot, "generation", 0))

    def max_available_replicas_batch(
        self, req: MaxAvailableReplicasBatchRequest
    ) -> MaxAvailableReplicasBatchResponse:
        """Answer the whole unique-profile matrix from ONE vectorized
        estimator call — the [B, N] kernel the unary wire path throws away.
        The generation is read BEFORE computing: a member event landing
        mid-computation must make the answer look stale (re-queried next
        pass), never fresh."""
        name = self.estimator.cluster_name
        if req.clusters and name not in req.clusters:
            return MaxAvailableReplicasBatchResponse()
        gen = self.generation()
        dims = self.estimator.snapshot.dims
        u = len(req.rows)
        mat = np.zeros((u, len(dims)), np.int64)
        # project caller dims onto ours by name: unknown caller dims drop,
        # our dims absent from the caller's list read 0 — exactly the unary
        # path's resource_request.get(d, 0)
        for j_src, d in enumerate(req.dims):
            if d in dims:
                mat[:, dims.index(d)] = [row[j_src] for row in req.rows]
        out = (
            self.estimator.max_available_replicas(None, mat)
            if u
            else np.zeros(0, np.int32)
        )
        # ResourceQuota plugin parity with the unary path: a row carrying
        # a namespace is capped through the SAME plugin call the unary
        # handler makes, over the same projected request dict the unary
        # fallback client would send — the batch answer for (namespace,
        # profile) is the unary answer by construction (feature-gated,
        # like the unary path)
        if req.namespaces and self.estimator.quota_plugin is not None:
            from ..utils.features import RESOURCE_QUOTA_ESTIMATE, feature_gate

            if feature_gate.enabled(RESOURCE_QUOTA_ESTIMATE):
                out = np.asarray(out).copy()
                for j, ns in enumerate(req.namespaces[:u]):
                    if not ns:
                        continue
                    requirements = ReplicaRequirements(
                        resource_request={
                            d: int(q)
                            for d, q in zip(req.dims, req.rows[j])
                            if q > 0
                        },
                        namespace=ns,
                    )
                    cap = self.estimator.quota_plugin.estimate(
                        ns, requirements
                    )
                    if cap is not None:
                        out[j] = min(int(out[j]), max(int(cap), 0))
        return MaxAvailableReplicasBatchResponse(
            results=[
                ClusterBatchResult(
                    cluster=name,
                    max_replicas=[int(v) for v in out],
                    generation=gen,
                )
            ]
        )

    def get_generations(
        self, req: GetGenerationsRequest
    ) -> GetGenerationsResponse:
        name = self.estimator.cluster_name
        if req.clusters and name not in req.clusters:
            return GetGenerationsResponse()
        return GetGenerationsResponse(generations={name: self.generation()})


class MultiClusterEstimatorService:
    """One server PROCESS hosting many clusters' estimators, routed by
    ``request.cluster`` — the multiplexed deployment shape (the reference
    runs one estimator deployment per member; at hundreds of members an
    operator consolidates them, and the wire contract already carries the
    cluster name on every request, so the scheduler side is unchanged)."""

    def __init__(self, services: dict[str, EstimatorService]):
        self._services = services

    def max_available_replicas(
        self, req: MaxAvailableReplicasRequest
    ) -> MaxAvailableReplicasResponse:
        svc = self._services.get(req.cluster)
        if svc is None:
            raise KeyError(f"no estimator for cluster {req.cluster!r}")
        return svc.max_available_replicas(req)

    def get_unschedulable_replicas(
        self, req: UnschedulableReplicasRequest
    ) -> UnschedulableReplicasResponse:
        svc = self._services.get(req.cluster)
        if svc is None:
            raise KeyError(f"no estimator for cluster {req.cluster!r}")
        return svc.get_unschedulable_replicas(req)

    def max_available_replicas_batch(
        self, req: MaxAvailableReplicasBatchRequest
    ) -> MaxAvailableReplicasBatchResponse:
        """One RPC answers every hosted cluster's unique-profile vector —
        the O(servers) pass shape. A requested-but-unhosted cluster is
        simply absent from the response (the caller answers
        UnauthenticReplica for it, matching the unary path's KeyError)."""
        wanted = req.clusters or sorted(self._services)
        results: list[ClusterBatchResult] = []
        for name in wanted:
            svc = self._services.get(name)
            if svc is None:
                continue
            sub = MaxAvailableReplicasBatchRequest(
                clusters=[name], dims=req.dims, rows=req.rows,
                namespaces=req.namespaces,
            )
            results.extend(svc.max_available_replicas_batch(sub).results)
        return MaxAvailableReplicasBatchResponse(results=results)

    def get_generations(
        self, req: GetGenerationsRequest
    ) -> GetGenerationsResponse:
        wanted = req.clusters or sorted(self._services)
        return GetGenerationsResponse(
            generations={
                name: self._services[name].generation()
                for name in wanted
                if name in self._services
            }
        )


class EstimatorConnection:
    """One cluster's channel. ``call`` is the transport seam."""

    def __init__(self, cluster: str, service: EstimatorService):
        self.cluster = cluster
        self._service = service

    def call(self, method: str, request):
        # the in-proc seam records the SAME server-side span the gRPC
        # handlers do (trace shape is transport-independent); the caller
        # shares the process, so it nests under the caller's open span
        # directly — no metadata, no remote_parent, no network column
        from ..utils.tracing import tracer

        with tracer.server_span("estimator.serve", None, method=method):
            return self._dispatch(method, request)

    def _dispatch(self, method: str, request):
        if method == "MaxAvailableReplicas":
            return self._service.max_available_replicas(request)
        if method == "GetUnschedulableReplicas":
            return self._service.get_unschedulable_replicas(request)
        if method == "MaxAvailableReplicasBatch":
            handler = getattr(
                self._service, "max_available_replicas_batch", None
            )
            if handler is None:  # an old service build: negotiate fallback
                raise UnsupportedMethodError(method)
            return handler(request)
        if method == "GetGenerations":
            handler = getattr(self._service, "get_generations", None)
            if handler is None:
                raise UnsupportedMethodError(method)
            return handler(request)
        raise ValueError(f"unknown method {method}")


def _close(conn) -> None:
    close = getattr(conn, "close", None)
    if close is not None:
        try:
            close()
        except Exception as exc:  # noqa: BLE001 — teardown is best-effort
            log.debug("estimator connection close failed: %s", exc)


class EstimatorClientPool:
    """Scheduler-side connection cache + service discovery
    (client/cache.go + client/service.go). Discovery resolves
    ``{prefix}-{cluster}`` through a resolver callable — the DNS-by-
    convention analogue."""

    def __init__(
        self,
        resolver: Callable[[str], Optional[EstimatorService]],
        timeout_seconds: float = 3.0,
        max_workers: int = 32,
    ):
        self.resolver = resolver
        self.timeout = timeout_seconds
        self._conns: dict[str, EstimatorConnection] = {}
        self._lock = threading.Lock()
        # bounded shared executor for the fan-out: a raw Thread per cluster
        # per query (the previous shape) costs a ~8 MiB stack + spawn each
        # at thousands of members; the executor spawns lazily up to the
        # bound and reuses threads across passes. Context-propagating: the
        # per-cluster RPC spans must land in the wave that fanned out, not
        # in wave 0 on a bare pool thread
        from concurrent.futures import ThreadPoolExecutor

        from ..utils.tracing import ContextPropagatingExecutor

        self._executor = ContextPropagatingExecutor(ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="estimator-fanout"
        ))

    def connection(self, cluster: str) -> Optional[EstimatorConnection]:
        with self._lock:
            conn = self._conns.get(cluster)
        if conn is not None:
            return conn
        service = self.resolver(cluster)
        if service is None:
            return None
        # the resolver may hand back a ready connection (e.g. a
        # GrpcEstimatorConnection) or a bare service to wrap in-proc
        conn = service if hasattr(service, "call") else EstimatorConnection(cluster, service)
        with self._lock:
            winner = self._conns.setdefault(cluster, conn)
        if winner is not conn:  # lost an insert race: drop the extra channel
            _close(conn)
        return winner

    def evict(self, cluster: str, conn=None) -> None:
        """Drop a cached connection. When ``conn`` is given, evict only if it
        is still the cached one — a late failure must not tear down a
        channel a newer pass already re-resolved."""
        with self._lock:
            cached = self._conns.get(cluster)
            if cached is None or (conn is not None and cached is not conn):
                return
            del self._conns[cluster]
        _close(cached)

    def max_available_replicas(
        self,
        clusters: list[str],
        resource_request: dict[str, int],
        **req_kw,
    ) -> dict[str, int]:
        """Concurrent fan-out with one shared deadline
        (client/accurate.go:139-162). Clusters without a connection answer
        UnauthenticReplica (-1)."""
        from concurrent.futures import wait as _fwait

        results: dict[str, int] = {c: UNAUTHENTIC for c in clusters}

        def one(cluster: str) -> None:
            conn = self.connection(cluster)
            if conn is None:
                return
            from .accurate import conn_breaker_engaged

            if conn_breaker_engaged(conn):
                # breaker-open server: answer UnauthenticReplica NOW
                # instead of burning the fan-out on a doomed RPC (the
                # transport's own half-open probe heals the breaker)
                return
            try:
                resp = conn.call(
                    "MaxAvailableReplicas",
                    MaxAvailableReplicasRequest(
                        cluster=cluster, resource_request=resource_request, **req_kw
                    ),
                )
            except Exception as exc:  # noqa: BLE001 — any transport failure
                # transport failure answers UnauthenticReplica and drops the
                # cached channel — only if it is still this one, so a late
                # straggler cannot tear down a re-resolved healthy channel
                # (client/accurate.go error path + cache eviction). Logged:
                # a silently-evicted estimator looks identical to a cluster
                # that genuinely answered -1. Class name only at warning —
                # grpc error reprs are multi-line and orchestrators scrape
                # this process's merged stdout/stderr for JSON lines
                log.warning(
                    "estimator %s: MaxAvailableReplicas failed (%s); "
                    "answering UnauthenticReplica and evicting the channel",
                    cluster, type(exc).__name__,
                )
                log.debug("estimator %s failure detail", cluster,
                          exc_info=exc)
                self.evict(cluster, conn)
                return
            results[cluster] = resp.max_replicas

        futs = [self._executor.submit(one, c) for c in clusters]
        # one shared deadline for the whole fan-out; stragglers keep running
        # on the executor (their conn.call carries its own timeout, so they
        # drain) and keep writing to ``results`` — the caller's view must be
        # frozen at the deadline, hence the snapshot
        _fwait(futs, timeout=self.timeout)
        return dict(results)
