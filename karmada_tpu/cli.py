"""karmadactl-style operations (ref: pkg/karmadactl/karmadactl.go:98-178).

The reference CLI talks to a remote control plane; here every command is a
function over a ControlPlane handle (the in-proc apiserver seam), so the same
operations serve tests, the demo driver, and a future remote transport:

- lifecycle: init (local_up), join / unjoin (push), register / unregister
  (pull), addons
- ops: get / describe / top across clusters (via the search proxy +
  metrics adapter)
- migration: promote (import a member resource as template + policy)
- maintenance: cordon / uncordon, taint
- interpret: dry-run interpreter operations against a template
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from typing import TYPE_CHECKING

from .api.cluster import NO_EXECUTE, NO_SCHEDULE, PULL, Cluster, Taint
from .api.core import ObjectMeta
from .api.policy import (
    ClusterAffinity,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
)
from .utils.builders import new_cluster

if TYPE_CHECKING:  # runtime imports are DEFERRED: controlplane/search/
    # member all reach the estimator (and therefore jax) at import time,
    # and this is an entry module — the GL005 cold-start contract. The
    # lint verb additionally depends on it: the IR/dep tiers must set
    # XLA_FLAGS before this process's FIRST jax import or the sharded
    # spec variants cannot materialize their >=2-device mesh.
    from .controlplane import ControlPlane
    from .search import ProxyRequest
    from .utils.member import MemberCluster


def _proxy_request(**kw) -> "ProxyRequest":
    from .search import ProxyRequest

    return ProxyRequest(**kw)

CORDON_TAINT_KEY = "node.karmada.io/unschedulable"  # cordon analogue


# --------------------------------------------------------------------------
# remote backend: administer a plane this process did NOT construct
# --------------------------------------------------------------------------


def _plural_of() -> dict[str, tuple[str, str]]:
    """gvk -> (REST path prefix, plural), derived by inverting the proxy
    server's route table so the two sides can never drift apart."""
    from .search.proxyserver import _PLURALS

    out = {}
    for plural, gvk in _PLURALS.items():
        group_version = gvk.rsplit("/", 1)[0]
        prefix = "api/v1" if group_version == "v1" else f"apis/{group_version}"
        out[gvk] = (prefix, plural)
    return out


class _RemoteProxyChain:
    """The ``Proxy.connect`` surface over the wire: fleet-wide reads serve
    from the bus mirror (the karmada tier), cluster-scoped requests ride
    the HTTP cluster-proxy passthrough (the cluster tier).
    Ref: pkg/karmadactl talks to the aggregated apiserver the same way."""

    def __init__(self, store, proxy_target: str, token: str):
        self.store = store
        self.proxy_target = proxy_target
        self.token = token

    def _http(self, path: str, timeout: float = 10.0):
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"http://{self.proxy_target}{path}",
            headers={"Authorization": f"Bearer {self.token}"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def connect(self, req: "ProxyRequest"):
        from .interpreter.webhook import resource_from_dict
        from .search.proxy import ProxyResponse

        if req.cluster is None:
            # fleet scope: mirror of the control-plane store (karmada tier)
            if req.verb == "get":
                key = (
                    f"{req.namespace}/{req.name}" if req.namespace else req.name
                )
                obj = self.store.get("Resource", key)
                if obj is not None and f"{obj.api_version}/{obj.kind}" == req.gvk:
                    return ProxyResponse(served_by="karmada", obj=obj)
                return ProxyResponse(served_by="karmada", error="not found")
            if req.verb == "list":
                items = [
                    ("karmada", o)
                    for o in self.store.list("Resource", req.namespace or None)
                    if f"{o.api_version}/{o.kind}" == req.gvk
                    and all(
                        o.meta.labels.get(k) == v for k, v in req.labels.items()
                    )
                ]
                return ProxyResponse(served_by="karmada", items=items)
            return ProxyResponse(
                served_by="karmada", error=f"verb {req.verb} requires cluster routing"
            )
        base = (
            "/apis/cluster.karmada.io/v1alpha1/clusters/"
            f"{req.cluster}/proxy"
        )
        if req.verb == "logs":
            tail = req.options.get("tail")
            qs = f"?tailLines={tail}" if tail else ""
            status, body = self._http(
                f"{base}/api/v1/namespaces/{req.namespace}/pods/"
                f"{req.name}/log{qs}"
            )
            if status != 200:
                return ProxyResponse(served_by="cluster", error=body)
            return ProxyResponse(
                served_by="cluster", data=body.splitlines()
            )
        if req.verb in ("exec", "attach"):
            # the streaming exec/attach subresource (chunked through the
            # proxy; a SubprocessExecRuntime member pipes a REAL process)
            import urllib.parse as _q

            cmd = (req.options or {}).get("command") or []
            qs = "&".join(f"command={_q.quote(str(c))}" for c in cmd)
            sub = "exec" if req.verb == "exec" else "attach"
            # a silent-but-running command sends no chunks: outlive the
            # member runtime's own 30s process bound with headroom
            status, body = self._http(
                f"{base}/api/v1/namespaces/{req.namespace}/pods/"
                f"{req.name}/{sub}" + (f"?{qs}" if qs else ""),
                timeout=float((req.options or {}).get("timeout", 60.0)),
            )
            if status != 200:
                return ProxyResponse(served_by="cluster", error=body)
            from .utils.member import split_exec_trailer

            lines, rc = split_exec_trailer(body.splitlines())
            return ProxyResponse(
                served_by="cluster",
                data={"stdout": "\n".join(lines), "rc": rc,
                      "lines": lines},
            )
        mapped = _plural_of().get(req.gvk)
        if mapped is None:
            return ProxyResponse(
                served_by="cluster", error=f"gvk {req.gvk} not proxied"
            )
        prefix, plural = mapped
        path = f"{base}/{prefix}/namespaces/{req.namespace}/{plural}"
        if req.verb == "get":
            status, body = self._http(f"{path}/{req.name}")
            if status != 200:
                return ProxyResponse(served_by="cluster", error=body)
            return ProxyResponse(
                served_by="cluster", obj=resource_from_dict(json.loads(body))
            )
        if req.verb == "list":
            qs = ""
            if req.labels:
                # forward the selector so a member API that honors it
                # prunes the list server-side (the client-side filter
                # below stays the guarantee either way)
                import urllib.parse as _q

                sel = ",".join(f"{k}={v}" for k, v in req.labels.items())
                qs = f"?labelSelector={_q.quote(sel)}"
            status, body = self._http(path + qs)
            if status != 200:
                return ProxyResponse(served_by="cluster", error=body)
            items = [
                (req.cluster, resource_from_dict(i))
                for i in json.loads(body).get("items", [])
            ]
            if req.labels:
                # the member API behind the passthrough may or may not
                # honor a labelSelector param; filtering here guarantees
                # the selector semantics either way (fleet-scope and
                # in-proc proxy branches already filter)
                items = [
                    (c, o)
                    for c, o in items
                    if all(
                        o.meta.labels.get(k) == v
                        for k, v in req.labels.items()
                    )
                ]
            return ProxyResponse(served_by="cluster", items=items)
        return ProxyResponse(
            served_by="cluster", error=f"verb {req.verb} not proxied"
        )


class RemotePlane:
    """A ControlPlane-shaped handle over the NETWORK surfaces only: state
    via the store bus (StoreReplica mirror + write-through), member access
    via the cluster-proxy HTTP server. Every ``cmd_*`` that touches only
    ``cp.store`` / ``cp.proxy`` works unchanged against it — the CLI can
    administer a plane it did not construct (VERDICT r3 item 5; ref:
    pkg/karmadactl/karmadactl.go:98-178)."""

    def __init__(
        self,
        bus_target: str,
        proxy_target: str = "",
        *,
        token: str = "admin-token",
        sync_timeout: float = 10.0,
    ):
        from .bus.agent import ReplicaStoreFacade
        from .bus.service import StoreReplica

        self._replica = StoreReplica(bus_target)
        self._replica.start()
        if not self._replica.wait_synced(sync_timeout):
            self._replica.close()
            raise RuntimeError(f"bus {bus_target}: sync timeout")
        self.store = ReplicaStoreFacade(self._replica)
        self.proxy = _RemoteProxyChain(self.store, proxy_target, token)

    def close(self) -> None:
        self._replica.close()

    def __enter__(self) -> "RemotePlane":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def cmd_init(**kw) -> ControlPlane:
    """Bootstrap a control plane (karmadactl init / operator install)."""
    from .controlplane import ControlPlane

    return ControlPlane(**kw)


def cmd_local_up(n_members: int = 3, **kw) -> ControlPlane:
    """hack/local-up-karmada.sh: control plane + n members (last one Pull)."""
    cp = cmd_init(**kw)
    for i in range(1, n_members + 1):
        cluster = new_cluster(f"member{i}", cpu="100", memory="200Gi")
        if i == n_members and n_members >= 3:
            cluster.spec.sync_mode = PULL
        cp.join_cluster(cluster)
    cp.settle()
    return cp


def cmd_join(
    cp: ControlPlane, name: str, member: Optional[MemberCluster] = None, **cluster_kw
) -> Cluster:
    """Push-mode join (pkg/karmadactl/join)."""
    cluster = new_cluster(name, **cluster_kw)
    cp.join_cluster(cluster, member)
    return cluster


def cmd_deinit(cp: ControlPlane) -> None:
    """Tear the control plane down (pkg/karmadactl/cmdinit deinit): unjoin
    every member (draining execution spaces), then drop all control-plane
    state so the instance can be garbage collected."""
    for name in list(cp.members.names()):
        cp.unjoin_cluster(name)
    cp.settle()
    for kind in list(cp.store.kinds()):
        for obj in list(cp.store.list(kind)):
            cp.store.delete(kind, obj.meta.namespaced_name)


def cmd_unjoin(cp: ControlPlane, name: str) -> None:
    cp.unjoin_cluster(name)


def cmd_token_create(cp: ControlPlane) -> str:
    """karmadactl token create: bootstrap token for pull-mode registration."""
    return cp.authority.create_token().token


def cmd_register(
    cp: ControlPlane,
    name: str,
    member: Optional[MemberCluster] = None,
    token: Optional[str] = None,
    **cluster_kw,
) -> Cluster:
    """Pull-mode register (pkg/karmadactl/register): kubeadm-style token ->
    CSR -> signed agent cert, then deploys the agent. Without a token the
    admin-kubeconfig path is used (direct join)."""
    if token is not None:
        record = cp.authority.submit_csr(name, token)
        if record is None:
            raise PermissionError(f"invalid or expired bootstrap token for {name}")
    cluster = new_cluster(name, **cluster_kw)
    cluster.spec.sync_mode = PULL
    cp.join_cluster(cluster, member)
    return cluster


def cmd_unregister(cp: ControlPlane, name: str) -> None:
    cp.unjoin_cluster(name)


def cmd_cordon(cp: ControlPlane, name: str) -> None:
    """Mark a cluster unschedulable (pkg/karmadactl/cordon)."""
    cluster = cp.store.get("Cluster", name)
    if cluster is None:
        raise KeyError(name)
    if not any(t.key == CORDON_TAINT_KEY for t in cluster.spec.taints):
        cluster.spec.taints.append(Taint(key=CORDON_TAINT_KEY, effect=NO_SCHEDULE))
        cp.store.apply(cluster)


def cmd_uncordon(cp: ControlPlane, name: str) -> None:
    cluster = cp.store.get("Cluster", name)
    if cluster is None:
        raise KeyError(name)
    before = len(cluster.spec.taints)
    cluster.spec.taints = [
        t for t in cluster.spec.taints if t.key != CORDON_TAINT_KEY
    ]
    if len(cluster.spec.taints) != before:
        cp.store.apply(cluster)


def cmd_taint(
    cp: ControlPlane, name: str, key: str, value: str = "", effect: str = NO_SCHEDULE,
    remove: bool = False,
) -> None:
    """pkg/karmadactl/cordon taint command analogue."""
    cluster = cp.store.get("Cluster", name)
    if cluster is None:
        raise KeyError(name)
    cluster.spec.taints = [
        t for t in cluster.spec.taints if not (t.key == key and t.effect == effect)
    ]
    if not remove:
        cluster.spec.taints.append(Taint(key=key, value=value, effect=effect))
    cp.store.apply(cluster)


def cmd_get(
    cp: ControlPlane,
    gvk: str,
    namespace: str = "",
    name: str = "",
    cluster: Optional[str] = None,
    labels: Optional[dict] = None,
):
    """Multi-cluster get/list through the proxy chain."""
    verb = "get" if name else "list"
    return cp.proxy.connect(
        _proxy_request(
            verb=verb, gvk=gvk, namespace=namespace, name=name,
            cluster=cluster, labels=dict(labels or {}),
        )
    )


def cmd_describe(cp: ControlPlane, gvk: str, namespace: str, name: str) -> str:
    """Aggregated description: template + binding + per-cluster status."""
    lines = [f"{gvk} {namespace}/{name}"]
    resp = cmd_get(cp, gvk, namespace, name)
    if resp.obj is None:
        return f"{gvk} {namespace}/{name}: not found"
    kind = gvk.rsplit("/", 1)[-1].lower()
    rb = cp.store.get(
        "ResourceBinding",
        f"{namespace}/{name}-{kind}" if namespace else f"{name}-{kind}",
    )
    if rb is not None:
        lines.append("placements:")
        for tc in rb.spec.clusters:
            lines.append(f"  {tc.name}: {tc.replicas} replicas")
        for item in rb.status.aggregated_status:
            lines.append(
                f"  {item.cluster_name}: applied={item.applied} health={item.health}"
            )
    return "\n".join(lines)


def cmd_top(cp: ControlPlane, workload_key: str):
    """Per-cluster + merged utilization (pkg/karmadactl/top)."""
    if cp.metrics_adapter is None:
        raise RuntimeError(
            "metrics adapter not installed (enable the "
            "karmada-metrics-adapter addon)"
        )
    samples = cp.metrics_adapter.resource_metrics(workload_key)
    merged = cp.metrics_adapter.merged_utilization(workload_key)
    return {"clusters": {s.cluster: s.value for s in samples}, "merged": merged}


def cmd_promote(
    cp: ControlPlane, cluster_name: str, gvk: str, namespace: str, name: str
) -> None:
    """Import an existing member-cluster resource into the control plane as a
    template + policy pinned to that cluster (pkg/karmadactl/promote)."""
    member = (
        cp.members.get(cluster_name) if hasattr(cp, "members") else None
    )
    if member is not None:
        obj = member.get(gvk, namespace, name)
    else:
        # remote plane: fetch the live object through the cluster proxy
        resp = cp.proxy.connect(
            _proxy_request(
                verb="get", gvk=gvk, namespace=namespace, name=name,
                cluster=cluster_name,
            )
        )
        obj = resp.obj if not resp.error else None
    if obj is None:
        raise KeyError(f"{gvk} {namespace}/{name} not found in {cluster_name}")
    import copy

    template = copy.deepcopy(obj)
    template.meta.resource_version = 0
    cp.store.apply(template)
    api_version, _, kind = gvk.rpartition("/")
    cp.store.apply(
        PropagationPolicy(
            meta=ObjectMeta(name=f"promote-{name}", namespace=namespace),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(
                        api_version=api_version, kind=kind,
                        namespace=namespace, name=name,
                    )
                ],
                placement=Placement(
                    cluster_affinity=ClusterAffinity(cluster_names=[cluster_name])
                ),
                # seamless takeover: adopt the live member object instead of
                # refusing on conflict (promote.go:738-798 sets Overwrite on
                # both the policy and the resource annotation)
                conflict_resolution="Overwrite",
            ),
        )
    )


def cmd_interpret(cp: ControlPlane, template, operation: str, **kw):
    """Dry-run an interpreter operation (pkg/karmadactl/interpret)."""
    interp = cp.interpreter
    if operation == "GetReplicas":
        return interp.get_replicas(template)
    if operation == "ReviseReplica":
        return interp.revise_replica(template, kw["replicas"])
    if operation == "InterpretHealth":
        return interp.interpret_health(template)
    if operation == "ReflectStatus":
        return interp.reflect_status(template)
    if operation == "GetDependencies":
        return interp.get_dependencies(template)
    if operation == "AggregateStatus":
        return interp.aggregate_status(template, kw.get("items", []))
    raise ValueError(f"unknown operation {operation}")


def cmd_logs(
    cp: ControlPlane,
    cluster: str,
    namespace: str,
    pod: str,
    tail: Optional[int] = None,
) -> list[str]:
    """karmadactl logs: pod logs through the clusters/{name}/proxy
    passthrough (pkg/karmadactl/logs)."""
    resp = cp.proxy.connect(
        _proxy_request(
            verb="logs", gvk="v1/Pod", namespace=namespace, name=pod,
            cluster=cluster, options={"tail": tail},
        )
    )
    if resp.error:
        raise RuntimeError(resp.error)
    return resp.data


def cmd_exec(
    cp: ControlPlane, cluster: str, namespace: str, pod: str, command: list[str]
) -> dict:
    """karmadactl exec: run a command in a member pod via the proxy
    (pkg/karmadactl/exec)."""
    resp = cp.proxy.connect(
        _proxy_request(
            verb="exec", gvk="v1/Pod", namespace=namespace, name=pod,
            cluster=cluster, options={"command": list(command)},
        )
    )
    if resp.error:
        raise RuntimeError(resp.error)
    return resp.data


def cmd_attach(
    cp: ControlPlane, cluster: str, namespace: str, pod: str
) -> list[str]:
    """karmadactl attach: stream the pod's output (pkg/karmadactl/attach) —
    in-proc this is the log stream from the runtime seam."""
    return cmd_logs(cp, cluster, namespace, pod)


ADDONS = (
    "karmada-descheduler",
    "karmada-scheduler-estimator",
    "karmada-search",
    "karmada-metrics-adapter",
)


def cmd_addons(cp: ControlPlane, enable: Sequence[str] = (), disable: Sequence[str] = ()):
    """Toggle optional components (pkg/karmadactl/addons: estimator,
    descheduler, search, metrics-adapter)."""
    from .controllers import Descheduler
    from .metricsadapter import MetricsAdapter

    state = {}
    for name in enable:
        if name not in ADDONS:
            raise ValueError(f"unknown addon {name}")
        if name == "karmada-descheduler":
            if cp.descheduler is None:
                cp.descheduler = Descheduler(
                    cp.store, cp.runtime, cp.members, clock=cp.clock
                )
            cp.descheduler.active = True
        elif name == "karmada-scheduler-estimator":
            cp.enable_accurate_estimators()
        elif name == "karmada-metrics-adapter" and cp.metrics_adapter is None:
            cp.metrics_adapter = MetricsAdapter(cp.members)
        elif name == "karmada-search":
            cp.search.resync()
        state[name] = "enabled"
    for name in disable:
        if name not in ADDONS:
            raise ValueError(f"unknown addon {name}")
        if name == "karmada-descheduler":
            # the ticker registration is permanent; deactivate in place so
            # disable actually stops reclaim and re-enable can't double-tick
            if cp.descheduler is not None:
                cp.descheduler.active = False
        elif name == "karmada-scheduler-estimator":
            cp.disable_accurate_estimators()
        elif name == "karmada-metrics-adapter":
            cp.metrics_adapter = None
        elif name == "karmada-search":
            cp.search.disable()
        state[name] = "disabled"
    return state


# --------------------------------------------------------------------------
# generic resource verbs (ref: pkg/karmadactl/karmadactl.go:98-178 — the
# kubectl-style apply/delete/patch/label/annotate/api-resources surface;
# subdirs pkg/karmadactl/{apply,patch,...}). Every verb runs over a
# ControlPlane-SHAPED handle: in-proc cp or RemotePlane — remote writes
# ride the store bus and the PLANE's admission chain validates them
# server-side, exactly like kubectl hitting the aggregated apiserver.
# --------------------------------------------------------------------------


def _load_manifests(text: str) -> list[dict]:
    """Parse manifests: a JSON object, a JSON array, a {kind: List,
    items: [...]} envelope, or (when available) multi-document YAML."""
    text = text.strip()
    docs: list = []
    if text.startswith(("{", "[")):
        data = json.loads(text)
        docs = data if isinstance(data, list) else [data]
    else:
        try:
            import yaml  # type: ignore[import-not-found]
        except ImportError as exc:  # JSON-only environment
            raise ValueError(
                "manifest is not JSON and no YAML parser is available"
            ) from exc
        docs = [d for d in yaml.safe_load_all(text) if d]
    out: list[dict] = []
    for d in docs:
        if isinstance(d, dict) and d.get("kind") == "List":
            out.extend(d.get("items") or [])
        else:
            out.append(d)
    return out


def _manifest_to_obj(manifest: dict):
    """k8s-style manifest -> typed object. Kinds the bus codec knows
    (karmada-native CRs) decode through the registry (metadata -> meta);
    anything else becomes a template ``Resource`` — the store's workload
    representation (what the detector matches policies against)."""
    from .bus.service import kind_registry
    from .utils.codec import from_jsonable

    kind = manifest.get("kind", "")
    reg = kind_registry()
    if kind in reg and kind != "Resource":
        from .api.versioning import maybe_upgrade

        manifest = maybe_upgrade(kind, manifest)
        d = {k: v for k, v in manifest.items() if k not in (
            "apiVersion", "kind",
        )}
        if "metadata" in d and "meta" not in d:
            d["meta"] = d.pop("metadata")
        return from_jsonable(reg[kind], d)
    from .interpreter.webhook import resource_from_dict

    return resource_from_dict(manifest)


def _resolve(cp, kind: str, namespace: str, name: str):
    """(store_kind, key, obj) for a verb target. ``kind`` is a registry
    kind ("PropagationPolicy"), or a gvk ("apps/v1/Deployment") / bare
    workload kind ("Deployment") for template Resources."""
    from .bus.service import kind_registry

    key = f"{namespace}/{name}" if namespace else name
    if "/" not in kind and kind in kind_registry() and kind != "Resource":
        return kind, key, cp.store.get(kind, key)
    obj = cp.store.get("Resource", key)
    if obj is not None and "/" in kind:
        if f"{obj.api_version}/{obj.kind}" != kind:
            return "Resource", key, None
    elif obj is not None and kind not in ("", "Resource", obj.kind):
        return "Resource", key, None
    return "Resource", key, obj


def _merge_patch(doc, patch):
    """RFC 7386 JSON merge patch (kubectl patch --type=merge)."""
    if not isinstance(patch, dict):
        return patch
    out = dict(doc) if isinstance(doc, dict) else {}
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = _merge_patch(out.get(k), v)
    return out


def cmd_apply(cp, manifests: Sequence[dict]) -> list[str]:
    """Create-or-update each manifest through the (possibly remote) store;
    the plane's admission chain validates server-side."""
    from .utils.store import obj_key, obj_kind

    applied = []
    for m in manifests:
        obj = _manifest_to_obj(m)
        cp.store.apply(obj)
        applied.append(f"{obj_kind(obj)}/{obj_key(obj)}")
    return applied


def cmd_delete(
    cp, kind: str, namespace: str, name: str, *, force: bool = False
) -> bool:
    store_kind, key, obj = _resolve(cp, kind, namespace, name)
    if obj is None:
        return False
    return bool(cp.store.delete(store_kind, key, force=force))


def cmd_patch(
    cp, kind: str, namespace: str, name: str, patch, patch_type: str = "merge"
):
    """Patch an object: ``merge`` (RFC 7386) or ``json`` (RFC 6902 ops).
    Spec changes bump the generation, mirroring the apiserver contract
    controllers reconcile against."""
    from .bus.service import decode_object
    from .interpreter.webhook import apply_json_patch
    from .utils.codec import to_jsonable

    store_kind, key, obj = _resolve(cp, kind, namespace, name)
    if obj is None:
        raise KeyError(f"{kind} {key} not found")
    doc = to_jsonable(obj)
    if patch_type == "merge":
        patched = _merge_patch(doc, patch)
    elif patch_type == "json":
        patched = apply_json_patch(doc, patch)
    else:
        raise ValueError(f"unknown patch type {patch_type!r}")
    new = decode_object(store_kind, json.dumps(patched))
    if to_jsonable(new).get("spec") != doc.get("spec"):
        new.meta.generation = obj.meta.generation + 1
    cp.store.apply(new)  # remote facades return the rv, not the object
    return new


def _mutate_meta_map(
    cp, kind: str, namespace: str, name: str, changes: Sequence[str],
    attr: str,
):
    from .bus.service import decode_object, encode_object

    store_kind, key, obj = _resolve(cp, kind, namespace, name)
    if obj is None:
        raise KeyError(f"{kind} {key} not found")
    # work on a codec round-trip COPY: store/mirror gets return the live
    # object, and mutating it before apply would make a rejected write
    # visible anyway (and defeat old-vs-new comparison in-proc)
    obj = decode_object(store_kind, encode_object(obj))
    mapping = dict(getattr(obj.meta, attr))
    for ch in changes:
        if ch.endswith("-") and "=" not in ch:
            mapping.pop(ch[:-1], None)
        else:
            k, sep, v = ch.partition("=")
            if not sep:
                raise ValueError(f"expected KEY=VALUE or KEY-, got {ch!r}")
            mapping[k] = v
    setattr(obj.meta, attr, mapping)
    cp.store.apply(obj)  # remote facades return the rv, not the object
    return obj


def cmd_create(cp, manifests: Sequence[dict]) -> list[str]:
    """Create-only write (karmadactl create / kubectl create): unlike
    ``apply`` an existing object is an AlreadyExists error, not an update.
    Ref: pkg/karmadactl/karmadactl.go:98-178 (create verb wiring)."""
    from .utils.store import obj_key, obj_kind

    created = []
    objs = []
    seen: set = set()
    for m in manifests:
        obj = _manifest_to_obj(m)
        kind, key = obj_kind(obj), obj_key(obj)
        # batch-wide existence precheck (catches duplicates WITHIN the
        # file too) before the first write; admission still runs per
        # apply, so like kubectl an admission rejection mid-file reports
        # what was already created rather than rolling it back
        if (kind, key) in seen or cp.store.get(kind, key) is not None:
            raise ValueError(f"{kind} {key!r} already exists")
        seen.add((kind, key))
        objs.append((obj, f"{kind}/{key}"))
    for obj, ref in objs:
        try:
            cp.store.apply(obj)
        except Exception as exc:
            raise ValueError(
                f"{ref} rejected: {exc}"
                + (f" (already created: {', '.join(created)})" if created else "")
            ) from exc
        created.append(ref)
    return created


def cmd_edit(cp, kind: str, namespace: str, name: str, *, editor=None):
    """kubectl-style edit: dump the object to a temp file, run the user's
    editor on it, apply the result if it changed. ``editor`` is the command
    line (defaults to $KUBE_EDITOR / $EDITOR / vi, as kubectl resolves it);
    returns the applied object or None when the buffer was left unchanged.
    Ref: pkg/karmadactl/edit/edit.go (NewCmdEdit wraps kubectl's editor
    flow against the karmada control plane)."""
    import os
    import shlex
    import subprocess
    import tempfile

    from .bus.service import decode_object
    from .utils.codec import to_jsonable

    store_kind, key, obj = _resolve(cp, kind, namespace, name)
    if obj is None:
        raise KeyError(f"{kind} {key} not found")
    doc = to_jsonable(obj)
    text = json.dumps(doc, indent=2, sort_keys=True)
    ed = (
        editor
        or os.environ.get("KUBE_EDITOR")
        or os.environ.get("EDITOR")
        or "vi"
    )
    fd, path = tempfile.mkstemp(suffix=".json", prefix="karmadactl-edit-")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        subprocess.run(f"{ed} {shlex.quote(path)}", shell=True, check=True)
        with open(path) as f:
            new_text = f.read()
        new_doc = json.loads(new_text)
        if new_doc == doc:
            os.unlink(path)
            return None  # "Edit cancelled, no changes made."
        # identity is immutable under edit (kubectl rejects primitive
        # changes): a changed name/namespace/kind would silently CREATE a
        # new object under another store key, leaving the edited one as-is
        for field, depth in (("kind", ()), ("name", ("meta",)),
                             ("namespace", ("meta",))):
            old_v, new_v = doc, new_doc
            for seg in depth:
                old_v = (old_v or {}).get(seg)
                new_v = (new_v or {}).get(seg)
            if (old_v or {}).get(field) != (new_v or {}).get(field):
                raise ValueError(
                    f"edit may not change {'.'.join(depth + (field,))}"
                )
        new = decode_object(store_kind, json.dumps(new_doc))
        # canonical-form comparison, same as cmd_patch: a key the codec
        # discards must not bump generation / wake controllers
        if to_jsonable(new).get("spec") != doc.get("spec"):
            new.meta.generation = obj.meta.generation + 1
        cp.store.apply(new)
    except Exception:
        # a post-editor failure (parse error, identity change, admission
        # rejection) must NOT destroy the user's edits: keep the buffer
        # and report where it lives, as kubectl does
        print(f"edit buffer preserved at {path}", file=sys.stderr)
        raise
    else:
        os.unlink(path)
    return new


def cmd_explain(path: str) -> str:
    """Field documentation for an API kind (karmadactl explain). The
    reference serves this from the apiserver's OpenAPI schema
    (pkg/karmadactl/explain/); here the registry's dataclasses ARE the
    schema, so explain reflects over them — same dotted-path grammar
    (``PropagationPolicy.spec.placement``), offline."""
    import dataclasses
    import typing

    from .bus.service import kind_registry

    kind, _, rest = path.partition(".")
    reg = kind_registry()
    cls = reg.get(kind)
    if cls is None:
        known = ", ".join(sorted(reg))
        raise KeyError(f"unknown kind {kind!r}; served kinds: {known}")

    import types as _types

    def unwrap(tp):
        """Optional[X] -> X; list[X]/dict[K,V] pass through for display."""
        origin = typing.get_origin(tp)
        if origin is typing.Union or origin is _types.UnionType:
            args = [a for a in typing.get_args(tp) if a is not type(None)]
            if len(args) == 1:
                return unwrap(args[0])
        return tp

    def type_name(tp) -> str:
        tp = unwrap(tp)
        origin = typing.get_origin(tp)
        if origin in (list, dict):
            args = ", ".join(type_name(a) for a in typing.get_args(tp))
            return f"{origin.__name__}[{args}]"
        return getattr(tp, "__name__", str(tp))

    def element(tp):
        """The dataclass to descend into (through Optional/list/dict)."""
        tp = unwrap(tp)
        origin = typing.get_origin(tp)
        if origin is list:
            return element(typing.get_args(tp)[0])
        if origin is dict:
            return element(typing.get_args(tp)[1])
        return tp if dataclasses.is_dataclass(tp) else None

    # descend the dotted path
    walked = [kind]
    for seg in [s for s in rest.split(".") if s]:
        if not dataclasses.is_dataclass(cls):
            raise KeyError(
                f"{'.'.join(walked)} is a scalar ({type_name(cls)}); "
                f"cannot descend into {seg!r}"
            )
        hints = typing.get_type_hints(cls)
        match = next(
            (f for f in dataclasses.fields(cls) if f.name == seg), None
        )
        if match is None:
            have = ", ".join(f.name for f in dataclasses.fields(cls))
            raise KeyError(
                f"field {seg!r} does not exist in {'.'.join(walked)}; "
                f"fields: {have}"
            )
        nxt = element(hints[match.name])
        cls = nxt if nxt is not None else unwrap(hints[match.name])
        walked.append(seg)

    lines = [f"KIND:     {kind}", f"PATH:     {'.'.join(walked)}", ""]
    doc = (getattr(cls, "__doc__", "") or "").strip().splitlines()
    if doc:
        lines += ["DESCRIPTION:", f"     {doc[0]}", ""]
    if dataclasses.is_dataclass(cls):
        lines.append("FIELDS:")
        hints = typing.get_type_hints(cls)
        for f in dataclasses.fields(cls):
            tn = type_name(hints[f.name])
            mark = " <required>" if (
                f.default is dataclasses.MISSING
                and f.default_factory is dataclasses.MISSING
            ) else ""
            lines.append(f"   {f.name}\t<{tn}>{mark}")
    else:
        lines.append(f"TYPE:     {type_name(cls)}")
    return "\n".join(lines)


def cmd_completion(shell: str = "bash") -> str:
    """Shell completion script generated from the live parser (karmadactl
    completion; ref pkg/karmadactl/karmadactl.go — cobra emits these).
    Bash and zsh (via bashcompinit) share the emitted script."""
    if shell not in ("bash", "zsh"):
        raise ValueError(f"unsupported shell {shell!r} (bash or zsh)")
    parser, sub = build_parser()
    cmds = sorted(sub.choices)
    # global flags reflected from the live parser, like the per-subcommand
    # ones — a new top-level flag must not be invisible to completion
    global_flags = sorted(
        opt
        for a in parser._actions
        for opt in a.option_strings
        if opt.startswith("--")
    )
    flag_lines = []
    for name, sp in sorted(sub.choices.items()):
        flags = sorted(
            opt
            for a in sp._actions
            for opt in a.option_strings
            if opt.startswith("--")
        )
        flag_lines.append(f'    {name}) opts="{" ".join(flags)}" ;;')
    body = "\n".join(flag_lines)
    # value-taking global flags: the word AFTER one is its value, not the
    # subcommand (``--bus host:1234 apply`` must resolve cmd=apply)
    valued = sorted(
        opt
        for a in parser._actions
        for opt in a.option_strings
        # store_true / help have nargs == 0; plain store has nargs None
        if opt.startswith("--") and a.nargs != 0
    )
    zsh_boot = (
        "autoload -U +X bashcompinit && bashcompinit\n"
        "autoload -U +X compinit && compinit\n"
        if shell == "zsh"
        else ""
    )
    return f"""# karmadactl-tpu completion ({shell}); source this file
{zsh_boot}_karmadactl_tpu() {{
  local cur cmd opts skip
  COMPREPLY=()
  cur="${{COMP_WORDS[COMP_CWORD]}}"
  cmd=""
  skip=0
  for w in "${{COMP_WORDS[@]:1:COMP_CWORD-1}}"; do
    if [ "$skip" = 1 ]; then skip=0; continue; fi
    case "$w" in
      {'|'.join(valued)}) skip=1 ;;
      -*) ;;
      *) cmd="$w"; break ;;
    esac
  done
  if [ -z "$cmd" ]; then
    COMPREPLY=( $(compgen -W "{' '.join(cmds)} {' '.join(global_flags)}" -- "$cur") )
    return 0
  fi
  case "$cmd" in
{body}
    *) opts="" ;;
  esac
  COMPREPLY=( $(compgen -W "$opts" -- "$cur") )
  return 0
}}
complete -F _karmadactl_tpu karmadactl-tpu
"""


def cmd_label(cp, kind, namespace, name, changes):
    """kubectl-style label mutation: KEY=VALUE adds/overwrites, KEY-
    removes."""
    return _mutate_meta_map(cp, kind, namespace, name, changes, "labels")


def cmd_annotate(cp, kind, namespace, name, changes):
    return _mutate_meta_map(cp, kind, namespace, name, changes, "annotations")


#: kinds stored by bare name (no namespace segment in the store key) —
#: discovery must say so or clients will address them as ns/name
_CLUSTER_SCOPED = {
    "Cluster", "ClusterPropagationPolicy", "ClusterOverridePolicy",
    "ClusterResourceBinding", "ResourceRegistry", "Remedy",
    "ClusterTaintPolicy", "Karmada", "ResourceInterpreterCustomization",
    "ResourceInterpreterWebhookConfiguration", "WorkloadRebalancer",
}


def _format_get(doc, output: str, gvk: str) -> str:
    """kubectl -o rendering for get results. ``doc`` is either one
    jsonable object or a list of {cluster, object} rows."""
    rows = doc if isinstance(doc, list) else [{"cluster": "", "object": doc}]

    def meta(o):
        return o.get("meta") or o.get("metadata") or {}

    if output == "yaml":
        import yaml

        return yaml.safe_dump(doc, sort_keys=False).rstrip()
    if output == "name":
        kind = gvk.rsplit("/", 1)[-1].lower()
        return "\n".join(
            f"{kind}/{meta(r['object']).get('name', '')}" for r in rows
        )
    if output == "wide":
        # kubectl's wide table, multi-cluster flavored: one line per
        # (cluster, object) with the status fields the aggregated
        # deployment view carries
        out = [f"{'CLUSTER':16} {'NAMESPACE':12} {'NAME':24} "
               f"{'READY':8} {'GENERATION':10}"]
        for r in rows:
            o = r["object"]
            m = meta(o)
            st = o.get("status") or {}
            ready = (
                f"{st.get('readyReplicas', st.get('ready_replicas', 0))}"
                f"/{(o.get('spec') or {}).get('replicas', '-')}"
            )
            out.append(
                f"{r.get('cluster', '') or '-':16} "
                f"{m.get('namespace', '') or '-':12} "
                f"{m.get('name', ''):24} {ready:8} "
                f"{m.get('generation', 0):<10}"
            )
        return "\n".join(out)
    return json.dumps(doc)


def cmd_api_resources(cp) -> list[dict]:
    """The discovery surface (karmadactl api-resources): registry kinds
    plus the proxied workload plurals."""
    from .bus.service import kind_registry
    from .search.proxyserver import _PLURALS

    out = [
        {"kind": k, "namespaced": k not in _CLUSTER_SCOPED,
         "source": "karmada"}
        for k in sorted(kind_registry())
    ]
    out += [
        {"kind": gvk, "plural": plural, "source": "cluster-proxy"}
        for plural, gvk in sorted(_PLURALS.items())
    ]
    return out


def build_parser() -> tuple:
    """The argparse surface, shared by ``main`` and ``cmd_completion``.
    Returns (parser, subparsers)."""
    parser = argparse.ArgumentParser(prog="karmadactl-tpu")
    parser.add_argument("--bus", default="", help="remote plane bus host:port")
    parser.add_argument("--proxy", default="", help="cluster proxy host:port")
    parser.add_argument("--token", default="admin-token")
    sub = parser.add_subparsers(dest="command", required=True)

    lu = sub.add_parser("local-up", help="bootstrap a demo control plane")
    lu.add_argument("--members", type=int, default=3)
    lu.add_argument(
        "--processes", action="store_true",
        help="spawn plane/solver/estimator/agent as separate OS processes "
        "(hack/local-up-karmada.sh analogue) and stay up",
    )

    g = sub.add_parser("get", help="multi-cluster get/list")
    g.add_argument("gvk")
    g.add_argument("--namespace", default="default")
    g.add_argument("--name", default="")
    g.add_argument("--cluster", default="")
    g.add_argument("-l", "--selector", default="",
                   help="label selector: key=value[,key2=value2]")
    g.add_argument("-o", "--output", default="json",
                   choices=("json", "yaml", "name", "wide"))

    d = sub.add_parser("describe", help="aggregated describe")
    d.add_argument("gvk")
    d.add_argument("namespace")
    d.add_argument("name")

    lg = sub.add_parser("logs", help="pod logs via the cluster proxy")
    lg.add_argument("cluster")
    lg.add_argument("namespace")
    lg.add_argument("pod")
    lg.add_argument("--tail", type=int, default=None)

    for nm in ("cordon", "uncordon"):
        cd = sub.add_parser(nm, help=f"{nm} a cluster")
        cd.add_argument("name")

    tn = sub.add_parser("taint", help="taint a cluster")
    tn.add_argument("name")
    tn.add_argument("key")
    tn.add_argument("--value", default="")
    tn.add_argument("--effect", default=NO_SCHEDULE)
    tn.add_argument("--remove", action="store_true")

    pm = sub.add_parser("promote", help="import a member resource")
    pm.add_argument("cluster")
    pm.add_argument("gvk")
    pm.add_argument("namespace")
    pm.add_argument("name")

    ap = sub.add_parser("apply", help="apply manifests through the bus")
    ap.add_argument("-f", "--filename", required=True,
                    help="manifest file (JSON/YAML; '-' = stdin)")

    cr = sub.add_parser("create", help="create-only apply through the bus")
    cr.add_argument("-f", "--filename", required=True,
                    help="manifest file (JSON/YAML; '-' = stdin)")

    ed = sub.add_parser("edit", help="edit a resource in $EDITOR")
    ed.add_argument("kind")
    ed.add_argument("namespace")
    ed.add_argument("name")
    ed.add_argument("--editor", default=None,
                    help="editor command (default: $KUBE_EDITOR / $EDITOR)")

    ex = sub.add_parser(
        "explain",
        help="field docs for a served kind (KIND[.field...]), or — with "
        "a <ns>/<name> argument — the binding's placement decision "
        "chain from the provenance plane (/debug/explain): per-stage "
        "exclusion reasons, the selected affinity group, top-k "
        "candidates and the final assignment",
    )
    ex.add_argument(
        "path",
        help="KIND[.field.subfield...] for field docs, or <ns>/<name> "
        "for a placement explanation",
    )
    ex.add_argument(
        "--wave", type=int, default=None,
        help="pin the placement explanation to one wave id "
        "(default: the newest capture holding the binding)",
    )
    ex.add_argument(
        "--metrics", default="",
        help="HOST:PORT of the scheduling process's metrics endpoint; "
        "without it the CURRENT process's in-proc ExplainStore answers "
        "(useful under an embedded plane)",
    )
    ex.add_argument(
        "--json", dest="as_json", action="store_true",
        help="print the raw explanation document instead of the "
        "decision-chain view",
    )

    co = sub.add_parser("completion", help="emit a shell completion script")
    co.add_argument("shell", nargs="?", default="bash",
                    choices=("bash", "zsh"))

    dl = sub.add_parser("delete", help="delete a resource through the bus")
    dl.add_argument("kind", help="registry kind or workload gvk")
    dl.add_argument("namespace")
    dl.add_argument("name")
    dl.add_argument("--force", action="store_true",
                    help="bypass finalizer gating")

    pt = sub.add_parser("patch", help="patch a resource through the bus")
    pt.add_argument("kind")
    pt.add_argument("namespace")
    pt.add_argument("name")
    pt.add_argument("-p", "--patch", required=True,
                    help="patch document (JSON)")
    pt.add_argument("--type", dest="patch_type", default="merge",
                    choices=("merge", "json"))

    for nm in ("label", "annotate"):
        mu = sub.add_parser(nm, help=f"{nm} a resource through the bus")
        mu.add_argument("kind")
        mu.add_argument("namespace")
        mu.add_argument("name")
        mu.add_argument("changes", nargs="+",
                        help="KEY=VALUE to set, KEY- to remove")

    sub.add_parser("api-resources", help="discovery: served kinds")

    wu = sub.add_parser(
        "warmup",
        help="AOT-prewarm the scheduler's XLA traces from the trace "
        "manifest (kills the plane's cold start; run before serving or "
        "after deploying a new build)",
    )
    wu.add_argument(
        "--manifest", default="",
        help="trace-manifest path (default: KARMADA_TPU_TRACE_MANIFEST, "
        "else <cache dir>/trace_manifest.json)",
    )
    wu.add_argument(
        "--no-expand", action="store_true",
        help="compile only observed signatures (skip the next-bucket "
        "cap expansion)",
    )

    tr = sub.add_parser(
        "trace",
        help="wave-trace operations: `trace dump --metrics HOST:PORT` "
        "fetches /debug/traces from a running process (plane, solver, "
        "estimator, bus — any MetricsServer) and prints the span ring + "
        "per-wave phase summaries as JSON; `trace dump --stitch` "
        "additionally pulls every registered peer's ring and merges the "
        "cross-process wave trees (per-process + per-channel columns); "
        "`trace analyze RECORD` re-renders a flight-recorder JSONL "
        "record's attribution offline",
    )
    tr.add_argument("action", choices=("dump", "analyze"))
    tr.add_argument(
        "record", nargs="?", default="",
        help="flight-recorder JSONL path (trace analyze)",
    )
    tr.add_argument(
        "--metrics", default="",
        help="HOST:PORT of the target process's metrics endpoint; "
        "without it the CURRENT process's in-proc tracer dumps (useful "
        "under an embedded plane)",
    )
    tr.add_argument(
        "--wave", type=int, default=None,
        help="restrict the span dump to one wave id (dump), or pick the "
        "flight record for that wave (analyze; default: the last record)",
    )
    tr.add_argument(
        "--summary", action="store_true",
        help="print only the per-wave phase summaries",
    )
    tr.add_argument(
        "--stitch", action="store_true",
        help="pull /debug/traces from every peer (--peers, the dumped "
        "process's registered peers, or KARMADA_TPU_TRACE_PEERS) and "
        "merge the cross-process wave trees",
    )
    tr.add_argument(
        "--peers", default="",
        help="comma-separated name=host:port peer metrics endpoints for "
        "--stitch (overrides the dumped process's registry)",
    )

    tp = sub.add_parser(
        "top",
        help="plane-wide per-wave telemetry table from the history rings "
        "(`/debug/history`): latest wave per process (wall, coverage, "
        "bindings/s, rows packed/replayed, compiles, upload/fetch MB, "
        "per-channel RPCs, device bytes, queue depth) plus "
        "recent-window p50/p95 digests and live settle-latency "
        "quantiles off /metrics; `--watch` refreshes in place",
    )
    tp.add_argument(
        "--metrics", default="",
        help="HOST:PORT of a process's metrics endpoint; without it the "
        "CURRENT process's in-proc history answers (useful under an "
        "embedded plane)",
    )
    tp.add_argument(
        "--peers", default="",
        help="comma-separated name=host:port peer metrics endpoints "
        "(default: the target's registered peers, else "
        "KARMADA_TPU_TRACE_PEERS)",
    )
    tp.add_argument(
        "--window", type=int, default=64,
        help="history rows fetched per process (digests cover the same "
        "window)",
    )
    tp.add_argument("--watch", action="store_true",
                    help="refresh every --interval seconds until Ctrl-C")
    tp.add_argument("--interval", type=float, default=2.0)
    tp.add_argument("--json", dest="as_json", action="store_true",
                    help="print the raw aggregated document instead of "
                    "the table")

    qu = sub.add_parser(
        "quota",
        help="quota-plane operations: `quota status [--metrics HOST:PORT]` "
        "prints per-namespace limit/used/denied from the metrics endpoint "
        "(karmada_tpu_quota_limit / _used / _denied_total families)",
    )
    qu.add_argument("action", choices=("status",))
    qu.add_argument(
        "--metrics", default="",
        help="HOST:PORT of the plane's metrics endpoint; without it the "
        "CURRENT process's in-proc registry answers (useful under an "
        "embedded plane)",
    )

    li = sub.add_parser(
        "lint",
        help="run graftlint, the repo's two-tier static analyzer: AST "
        "tier (GL001 trace safety, GL002 trace-key completeness, GL003 "
        "env-flag registry, GL004 lock discipline, GL005 import hygiene, "
        "GL006 metric naming, GL007 bounded RPCs, GL008 span taxonomy, "
        "GL009 history series sources, GL010 reason taxonomy, GL011 "
        "lock-read discipline, GL012 budget-in-loop, GL013 bounded "
        "caches), with --ir the jaxpr-level kernel auditor (IR001 dtype "
        "discipline, IR002 host round-trips, IR003 const capture, IR004 "
        "trace-manifest fidelity, IR005 donation audit), with --dep the "
        "row-dependence certifier (IR006 row_coupled declarations, IR007 "
        "replicated-scan discipline), and with --all every tier at once",
    )
    li.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: karmada_tpu tools); "
        "with --ir, kernel family names to audit (default: all)",
    )
    li.add_argument("--format", choices=("text", "json"), default="text")
    li.add_argument(
        "--no-baseline", action="store_true",
        help="report findings grandfathered in graftlint_baseline.json too",
    )
    li.add_argument(
        "--ir", action="store_true",
        help="run the IR tier: abstractly trace every registered kernel "
        "entry point on CPU and audit the jaxprs — run before a plane "
        "rollout (docs/OPERATIONS.md)",
    )
    li.add_argument(
        "--dep", action="store_true",
        help="run the dep tier: certify every kernel's row_coupled "
        "declaration against its jaxpr (delta-safety) and the "
        "replicated-scan discipline in sharded variants",
    )
    li.add_argument(
        "--all", dest="all_tiers", action="store_true",
        help="run AST + IR + dep tiers in one invocation (merged exit "
        "code, per-tier timing) — the CI/rollout gate shape",
    )
    li.add_argument(
        "--manifest", default=None, metavar="PATH",
        help="IR tier: also audit a prewarm trace manifest (every record "
        "must re-trace to its recorded signature)",
    )
    li.add_argument(
        "--changed-only", action="store_true",
        help="scope every tier to files with uncommitted git changes "
        "(the pre-commit mode, see docs/DEVELOPMENT.md)",
    )
    return parser, sub


def cmd_lint(
    paths: Sequence[str] = (), *, fmt: str = "text", baseline: bool = True,
    ir: bool = False, dep: bool = False, all_tiers: bool = False,
    manifest: str | None = None, changed_only: bool = False,
) -> int:
    """The ``lint`` verb: run the repo's static analyzer
    (tools/graftlint) over ``paths`` (default: the package + tools).
    Works from a checkout — the analyzer rides beside the package, not
    inside it (it is a development gate, not a serving component). The
    verb DELEGATES to graftlint's own CLI so output shape, exit codes and
    defaults can never drift between the two surfaces."""
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(os.path.join(repo_root, "tools", "graftlint")):
        print(
            "error: graftlint not found — `lint` runs from a repo "
            "checkout (tools/graftlint/)",
            file=sys.stderr,
        )
        return 2
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from tools.graftlint.__main__ import main as graftlint_main

    argv = list(paths) + ["--root", repo_root, "--format", fmt]
    if not baseline:
        argv.append("--no-baseline")
    if ir:
        argv.append("--ir")
    if dep:
        argv.append("--dep")
    if all_tiers:
        argv.append("--all")
    if manifest is not None:
        argv += ["--manifest", manifest]
    if changed_only:
        argv.append("--changed-only")
    return graftlint_main(argv)


def cmd_trace_dump(
    metrics: str = "",
    wave: Optional[int] = None,
    summary: bool = False,
    stitch: bool = False,
    peers: str = "",
) -> dict:
    """The ``trace dump`` verb: the wave-trace ring + per-wave phase
    summaries, either from a remote process's ``/debug/traces`` endpoint
    (``metrics="host:port"``) or this process's in-proc tracer. The
    per-phase summary is the same shape the observability bench records
    (BENCH_OBS_r*.json), so a dumped wave reads against the docs table.

    ``stitch=True`` additionally pulls ``/debug/traces`` from every peer
    (``peers="name=host:port,..."`` wins, else the dumped process's own
    registered peers, else this process's registry incl.
    KARMADA_TPU_TRACE_PEERS) and merges the cross-process wave trees:
    remote handler roots re-parent under their originating client spans
    and per-process/per-channel self-time columns come out
    (utils.tracing.stitch_dumps)."""
    from .utils.tracing import (
        fetch_peer_dumps,
        register_peers_from_env,
        stitch_dumps,
        trace_debug_doc,
    )
    from .utils.tracing import peers as registered_peers

    if metrics:
        import urllib.request

        with urllib.request.urlopen(
            f"http://{metrics}/debug/traces", timeout=10
        ) as resp:
            doc = json.loads(resp.read().decode())
    else:
        doc = trace_debug_doc()
    if stitch:
        peer_map: dict = {}
        if peers:
            for part in peers.split(","):
                name, sep, addr = part.strip().partition("=")
                if sep and name and addr:
                    peer_map[name.strip()] = addr.strip()
        else:
            peer_map = dict(doc.get("peers") or {})
            if not peer_map:
                register_peers_from_env()
                peer_map = registered_peers()
        # never re-fetch the dumped process itself
        peer_map = {
            name: addr for name, addr in peer_map.items()
            if addr != metrics
        }
        doc = stitch_dumps(
            doc, fetch_peer_dumps(peer_map, wave=wave), wave=wave
        )
    if wave is not None:
        doc["spans"] = [s for s in doc["spans"] if s.get("wave") == wave]
        doc["waves"] = [w for w in doc["waves"] if w.get("wave") == wave]
    if summary:
        doc.pop("spans", None)
    return doc


def cmd_trace_analyze(path: str, wave: Optional[int] = None) -> dict:
    """The ``trace analyze`` verb: re-derive a flight-recorder record's
    attribution from its raw spans, offline. ``wave`` picks the record
    for that wave id (default: the newest record in the file); the
    result carries the recomputed summary, an ``identical`` flag proving
    the stitcher re-derives exactly what was recorded, and the rendered
    attribution table."""
    from .utils.tracing import analyze_record, load_flight_records

    records = load_flight_records(path)
    if not records:
        raise ValueError(f"{path}: no flight records")
    if wave is not None:
        matching = [r for r in records if r.get("wave") == wave]
        if not matching:
            raise ValueError(f"{path}: no flight record for wave {wave}")
        record = matching[-1]
    else:
        record = records[-1]
    return analyze_record(record)


def cmd_explain_placement(
    ref: str, wave: Optional[int] = None, metrics: str = ""
) -> dict:
    """The ``explain <ns>/<name>`` verb: one binding's placement
    decision chain from the provenance plane — either a remote
    process's ``/debug/explain`` endpoint (``metrics="host:port"``) or
    this process's in-proc ExplainStore. The answered document is THE
    ``/debug/explain?binding=`` shape, so the CLI, the HTTP surface and
    the flight recorder can never drift."""
    if metrics:
        import urllib.parse
        import urllib.request

        query = f"?binding={urllib.parse.quote(ref, safe='')}"
        if wave is not None:
            query += f"&wave={wave}"
        with urllib.request.urlopen(
            f"http://{metrics}/debug/explain{query}", timeout=10
        ) as resp:
            return json.loads(resp.read().decode())
    from .utils.explainstore import store as explain_store
    from .utils.tracing import tracer as _tracer

    return explain_store().debug_doc(
        binding=ref, wave=wave, proc=_tracer.proc
    )


#: the quota families `quota status` reads off the exposition — kept in
#: one place so the verb and its parser cannot drift
_QUOTA_FAMILIES = (
    "karmada_tpu_quota_limit",
    "karmada_tpu_quota_used",
    "karmada_tpu_quota_denied_total",
)


def _parse_exposition_lines(text: str, families) -> list:
    """(family, labels dict, value) rows for the requested families from
    Prometheus text exposition — enough of the format for the flat
    counter/gauge families the quota plane exports."""
    import re as _re

    out = []
    line_re = _re.compile(
        r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
        r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$"
    )
    label_re = _re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
    wanted = set(families)
    # single-pass unescape: sequential str.replace corrupts values with
    # literal backslashes (an escaped \\ followed by n would collapse to
    # a newline)
    esc = {"\\\\": "\\", '\\"': '"', "\\n": "\n"}
    unescape = _re.compile(r'\\\\|\\"|\\n')
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = line_re.match(line.strip())
        if m is None or m.group("name") not in wanted:
            continue
        labels = {
            k: unescape.sub(lambda mm: esc[mm.group(0)], v)
            for k, v in label_re.findall(m.group("labels") or "")
        }
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        out.append((m.group("name"), labels, value))
    return out


def cmd_quota_status(metrics: str = "") -> dict:
    """The ``quota status`` verb: per-namespace limit/used/denied, read
    from a running process's /metrics endpoint (``metrics="host:port"``)
    or this process's in-proc registry. The families are the quota
    plane's exposition (FRQ status controller sets limit/used; the
    scheduler's denial path counts denied), so the verb needs no store
    access — any scrapable plane answers."""
    if metrics:
        import urllib.request

        with urllib.request.urlopen(
            f"http://{metrics}/metrics", timeout=10
        ) as resp:
            text = resp.read().decode()
        rows = _parse_exposition_lines(text, _QUOTA_FAMILIES)
    else:
        from .utils.metrics import registry as _registry

        rows = _parse_exposition_lines(
            _registry.render(), _QUOTA_FAMILIES
        )
    namespaces: dict = {}
    for family, labels, value in rows:
        ns = labels.get("namespace", "")
        entry = namespaces.setdefault(
            ns, {"resources": {}, "denied_total": 0}
        )
        if family == "karmada_tpu_quota_denied_total":
            entry["denied_total"] = int(value)
            continue
        res = labels.get("resource", "")
        slot = entry["resources"].setdefault(res, {"limit": 0, "used": 0})
        slot["limit" if family.endswith("_limit") else "used"] = int(value)
    return {"namespaces": namespaces}


def exposition_quantiles(
    text: str, family: str, qs
) -> dict[float, dict[tuple, float]]:
    """Bucket-interpolated quantiles straight off Prometheus text
    exposition (ISSUE 12 satellite): parse ``{family}_bucket`` /
    ``{family}_count`` rows ONCE with the SAME ``_parse_exposition_
    lines`` helper the quota-status verb uses, then estimate every
    requested quantile via the shared ``utils.metrics.bucket_quantile``
    core — one interpolation rule for the live Histogram and every CLI
    reading a scrape, so operators stop eyeballing raw cumulative
    buckets. Returns {q: {non-le label tuple: value}}."""
    from .utils.metrics import bucket_quantile

    rows = _parse_exposition_lines(
        text, (family + "_bucket", family + "_count")
    )
    buckets: dict[tuple, list] = {}
    totals: dict[tuple, int] = {}
    for name, labels, value in rows:
        if name.endswith("_count"):
            key = tuple(sorted(labels.items()))
            totals[key] = int(value)
            continue
        le = labels.get("le")
        if le is None or le.lstrip("+") == "Inf":
            continue
        key = tuple(sorted(
            (k, v) for k, v in labels.items() if k != "le"
        ))
        buckets.setdefault(key, []).append((float(le), int(value)))
    out: dict[float, dict[tuple, float]] = {q: {} for q in qs}
    for key, bs in buckets.items():
        bs.sort()
        bounds = [b for b, _ in bs]
        counts = [c for _, c in bs]
        total = totals.get(key, counts[-1] if counts else 0)
        for q in qs:
            v = bucket_quantile(q, bounds, counts, total)
            if v is not None:
                out[q][key] = v
    return out


def exposition_quantile(
    text: str, family: str, q: float
) -> dict[tuple, float]:
    """One-quantile form of ``exposition_quantiles`` (same parse, same
    interpolation)."""
    return exposition_quantiles(text, family, (q,))[q]


def cmd_plane_top(
    metrics: str = "", peers: str = "", window: int = 64
) -> dict:
    """The ``top`` verb: aggregate ``/debug/history`` (and the
    settle-latency histogram off ``/metrics``) across the plane's
    processes into one document — the target endpoint (or this
    process's in-proc history), plus every registered peer. Unreachable
    peers degrade to an ``error`` entry; the reachable plane still
    renders."""
    import urllib.request

    from .utils import tracing as trc
    from .utils.history import history_for

    def fetch(addr: str) -> tuple[dict, str]:
        with urllib.request.urlopen(
            f"http://{addr}/debug/history?window={window}", timeout=3
        ) as resp:
            doc = json.loads(resp.read().decode())
        try:
            with urllib.request.urlopen(
                f"http://{addr}/metrics", timeout=3
            ) as resp:
                text = resp.read().decode()
        except Exception:  # noqa: BLE001 — digest-only degradation
            text = ""
        return doc, text

    peer_map: dict[str, str] = {}
    if peers:
        for part in peers.split(","):
            name, sep, addr = part.strip().partition("=")
            if sep and name.strip() and addr.strip():
                peer_map[name.strip()] = addr.strip()

    fetched: dict[str, tuple[dict, str]] = {}
    if metrics:
        doc, text = fetch(metrics)
        fetched[doc.get("proc") or "target"] = (doc, text)
        if not peer_map:
            peer_map = {
                n: a for n, a in (doc.get("peers") or {}).items()
                if a != metrics
            }
    else:
        from .utils.metrics import registry as _registry

        tr = trc.tracer
        doc = history_for(tr).debug_doc(window=window, proc=tr.proc)
        doc["peers"] = trc.peers()
        fetched[tr.proc] = (doc, _registry.render())
        if not peer_map:
            peer_map = trc.peers()
        if not peer_map:
            # parse the env WITHOUT registering: a read-only monitoring
            # verb must not flip the embedded plane's every later wave
            # close into stitched per-close sampling (peers() gates it)
            import os as _os

            raw = _os.environ.get("KARMADA_TPU_TRACE_PEERS", "")
            for part in raw.split(","):
                name, sep, addr = part.strip().partition("=")
                if sep and name.strip() and addr.strip():
                    peer_map[name.strip()] = addr.strip()

    # peers fetch CONCURRENTLY: N black-holed peers must cost one
    # timeout, not N serial ones (a --watch refresh blocks on this)
    todo = {
        name: addr for name, addr in sorted(peer_map.items())
        if name not in fetched
    }
    if todo:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(len(todo), 8)) as pool:
            futures = {
                name: pool.submit(fetch, addr)
                for name, addr in todo.items()
            }
        for name, fut in futures.items():
            try:
                fetched[name] = fut.result()
            except Exception as exc:  # noqa: BLE001 — peer down:
                # render the rest
                fetched[name] = (
                    {"error": f"{type(exc).__name__}: {exc}"}, ""
                )

    out: dict = {"window": window, "procs": {}}
    for name, (doc, text) in fetched.items():
        if "error" in doc:
            out["procs"][name] = {"error": doc["error"]}
            continue
        entry = {
            "cap": doc.get("cap"),
            "sampled": doc.get("sampled"),
            "evicted": doc.get("evicted"),
            "rows": doc.get("rows", []),
            "digests": doc.get("digests", {}),
        }
        if text:
            for fam, slot in (
                ("karmada_tpu_settle_seconds", "settle"),
                ("karmada_tpu_scheduler_pass_seconds", "pass"),
            ):
                by_q = exposition_quantiles(text, fam, (0.5, 0.95))
                p50 = by_q[0.5].get(())
                p95 = by_q[0.95].get(())
                if p50 is not None:
                    entry[f"{slot}_p50_s"] = round(p50, 6)
                if p95 is not None:
                    entry[f"{slot}_p95_s"] = round(p95, 6)
            # ISSUE 13 satellite: the per-process device-byte total the
            # PR 12 ledger publishes (summed over {kind,bucket}) and the
            # unschedulable/denied totals off the new reason family —
            # the history rows carry per-wave deltas; these are the
            # process-lifetime levels the aggregate used to drop
            levels = _parse_exposition_lines(
                text,
                (
                    "karmada_tpu_device_bytes",
                    "karmada_tpu_unschedulable_total",
                    "karmada_tpu_quota_denied_total",
                    "karmada_tpu_preemptions_total",
                    "karmada_tpu_desched_disruption_budget",
                    "karmada_tpu_desched_disruption_used",
                ),
            )
            totals = {"karmada_tpu_device_bytes": 0.0,
                      "karmada_tpu_unschedulable_total": 0.0,
                      "karmada_tpu_quota_denied_total": 0.0,
                      "karmada_tpu_preemptions_total": 0.0,
                      "karmada_tpu_desched_disruption_budget": 0.0,
                      "karmada_tpu_desched_disruption_used": 0.0}
            by_reason: dict = {}
            preempt_by_reason: dict = {}
            for fam, labels, value in levels:
                totals[fam] += value
                if fam == "karmada_tpu_unschedulable_total":
                    reason = labels.get("reason", "")
                    by_reason[reason] = (
                        by_reason.get(reason, 0) + int(value)
                    )
                elif fam == "karmada_tpu_preemptions_total":
                    reason = labels.get("reason", "")
                    preempt_by_reason[reason] = (
                        preempt_by_reason.get(reason, 0) + int(value)
                    )
            entry["device_bytes"] = int(
                totals["karmada_tpu_device_bytes"]
            )
            entry["unschedulable_total"] = int(
                totals["karmada_tpu_unschedulable_total"]
            )
            entry["quota_denied_total"] = int(
                totals["karmada_tpu_quota_denied_total"]
            )
            # ISSUE 14 satellite: the scarcity-plane levels — lifetime
            # preemptions (by reason) plus the descheduler's live
            # disruption budget/used pair
            entry["preemptions_total"] = int(
                totals["karmada_tpu_preemptions_total"]
            )
            entry["disruption_budget"] = int(
                totals["karmada_tpu_desched_disruption_budget"]
            )
            entry["disruption_used"] = int(
                totals["karmada_tpu_desched_disruption_used"]
            )
            if by_reason:
                entry["unschedulable_by_reason"] = dict(
                    sorted(by_reason.items())
                )
            if preempt_by_reason:
                entry["preemptions_by_reason"] = dict(
                    sorted(preempt_by_reason.items())
                )
        out["procs"][name] = entry
    return out


def render_top(doc: dict) -> str:
    """The ``top`` table: the latest wave row per process, then the
    recent-window digests (p50/p95 per headline series) and the live
    settle quantiles."""
    from .utils.history import render_history_table

    latest = []
    for name, entry in sorted(doc.get("procs", {}).items()):
        for row in entry.get("rows", [])[-1:]:
            row = dict(row)
            row["proc"] = name
            latest.append(row)
    lines = [render_history_table(latest)] if latest else [
        "(no history rows sampled yet)"
    ]
    for name, entry in sorted(doc.get("procs", {}).items()):
        if "error" in entry:
            lines.append(f"{name}: unreachable ({entry['error']})")
            continue
        series = (entry.get("digests") or {}).get("series", {})
        window = (entry.get("digests") or {}).get("window", 0)
        bits = []
        for key, label in (
            ("wall_s", "wall"),
            ("bindings_s", "bind/s"),
            ("coverage", "cover"),
            ("device_bytes", "devB"),
        ):
            d = series.get(key)
            if d:
                bits.append(
                    f"{label} p50 {d['p50']:.3g} p95 {d['p95']:.3g}"
                )
        for slot in ("settle", "pass"):
            if f"{slot}_p50_s" in entry:
                bits.append(
                    f"{slot} p50 {entry[f'{slot}_p50_s']:.3g}s "
                    f"p95 {entry.get(f'{slot}_p95_s', 0.0):.3g}s"
                )
        if "device_bytes" in entry:
            bits.append(f"devB {entry['device_bytes'] / 1e6:.2f}MB")
        if entry.get("unschedulable_total") or entry.get(
            "quota_denied_total"
        ):
            bits.append(
                f"unsched/denied {entry.get('unschedulable_total', 0)}"
                f"/{entry.get('quota_denied_total', 0)}"
            )
        if entry.get("preemptions_total"):
            bits.append(f"preempted {entry['preemptions_total']}")
        if entry.get("disruption_budget"):
            bits.append(
                f"disruption {entry.get('disruption_used', 0)}"
                f"/{entry['disruption_budget']}"
            )
        if entry.get("evicted"):
            bits.append(f"evicted {entry['evicted']}")
        if bits:
            lines.append(
                f"{name} (last {window} wave(s)): " + ", ".join(bits)
            )
    return "\n".join(lines)


def cmd_warmup(manifest: str = "", expand: bool = True) -> dict:
    """The ``warmup`` verb: replay the trace manifest through AOT
    compilation on the current backend (scheduler.prewarm.warmup), so a
    following plane/solver boot — or this process's first schedule pass —
    pays zero compile cost for covered fleet shapes."""
    from .scheduler.prewarm import warmup

    return warmup(manifest or None, expand=expand)


def lint_main(argv: Optional[list[str]] = None) -> int:
    """Console entry for the ``karmada-tpu-lint`` convenience script
    (pyproject [project.scripts]): ``karmada-tpu-lint --changed-only`` is
    the pre-commit hook body, ``karmada-tpu-lint --ir`` the pre-rollout
    audit — both delegate through the ``lint`` verb so the script, the
    verb and ``python -m tools.graftlint`` cannot drift."""
    if argv is None:
        argv = sys.argv[1:]
    return main(["lint", *argv])


def main(argv: Optional[list[str]] = None) -> int:
    """argparse front end. With ``--bus`` (and optionally ``--proxy``) the
    commands operate on a REMOTE plane over the wire — state through the
    store bus, member access through the cluster proxy; without it,
    ``local-up`` bootstraps a demo plane in-process (``--processes`` spawns
    the full multi-process deployment instead). Applies the parent's jax
    platform policy first — a CLI child of localup/the operator must not
    dial the single-client accelerator tunnel."""
    parser, _sub = build_parser()
    args = parser.parse_args(argv)

    # offline verbs: no plane, no bus
    if args.command == "explain":
        if "/" in args.path:
            # <ns>/<name>: the provenance plane's decision chain
            try:
                doc = cmd_explain_placement(
                    args.path, wave=args.wave, metrics=args.metrics
                )
            except Exception as exc:  # unreachable endpoint, bad JSON
                print(json.dumps({"error": str(exc)}))
                return 1
            if args.as_json:
                print(json.dumps(doc, indent=2))
            else:
                from .utils.explainstore import render_explanation

                print(render_explanation(doc.get("binding")))
            return 0 if doc.get("binding") is not None else 1
        try:
            print(cmd_explain(args.path))
        except KeyError as exc:
            print(json.dumps({"error": str(exc.args[0])}))
            return 1
        return 0
    if args.command == "completion":
        print(cmd_completion(args.shell))
        return 0
    if args.command == "lint":
        return cmd_lint(
            args.paths, fmt=args.format, baseline=not args.no_baseline,
            ir=args.ir, dep=args.dep, all_tiers=args.all_tiers,
            manifest=args.manifest, changed_only=args.changed_only,
        )
    if args.command == "trace":
        if args.action == "analyze":
            if not args.record:
                print(json.dumps(
                    {"error": "trace analyze needs a record path"}
                ))
                return 1
            try:
                doc = cmd_trace_analyze(args.record, wave=args.wave)
            except Exception as exc:  # missing/corrupt record file
                print(json.dumps({"error": str(exc)}))
                return 1
            table = doc.pop("table", "")
            print(json.dumps(doc, indent=2))
            if table:
                print(table, file=sys.stderr)
            return 0
        try:
            doc = cmd_trace_dump(
                args.metrics, wave=args.wave, summary=args.summary,
                stitch=args.stitch, peers=args.peers,
            )
        except Exception as exc:  # unreachable endpoint, bad JSON
            print(json.dumps({"error": str(exc)}))
            return 1
        print(json.dumps(doc, indent=2))
        return 0
    if args.command == "quota":
        try:
            doc = cmd_quota_status(args.metrics)
        except Exception as exc:  # unreachable endpoint, bad text
            print(json.dumps({"error": str(exc)}))
            return 1
        print(json.dumps(doc, indent=2))
        return 0
    if args.command == "top":
        import time as _time

        while True:
            try:
                doc = cmd_plane_top(
                    args.metrics, peers=args.peers, window=args.window
                )
            except KeyboardInterrupt:
                # Ctrl-C mid-fetch in --watch mode is a clean exit,
                # not a traceback
                return 0
            except Exception as exc:  # unreachable target endpoint
                print(json.dumps({"error": str(exc)}))
                if not args.watch:
                    return 1
                # a watch survives one failed scrape (target restarting)
                # and retries on the next interval
                doc = None
            if doc is not None:
                if args.as_json:
                    print(json.dumps(doc, indent=2))
                else:
                    print(render_top(doc))
            if not args.watch:
                return 0
            try:
                _time.sleep(args.interval)
            except KeyboardInterrupt:
                return 0
            print()  # blank separator between refreshes
    if args.command == "warmup":
        stats = cmd_warmup(args.manifest, expand=not args.no_expand)
        print(json.dumps(stats))
        # no manifest yet is a no-op boot optimization, not a failure;
        # per-record compile failures (stale manifest vs new build) are
        # reported in the JSON but only a total wipe-out exits nonzero
        return 1 if (stats["failed"] and not stats["compiled"]) else 0

    if args.command == "local-up":
        if args.processes:
            from .localup import LocalUp

            with LocalUp(members=args.members) as lup:
                print(json.dumps(lup.endpoints), flush=True)
                try:
                    while all(p.poll() is None for p in lup.procs.values()):
                        import time as _t

                        _t.sleep(1)
                except KeyboardInterrupt:
                    pass
            return 0
        cp = cmd_local_up(args.members)
        clusters = [c.name for c in cp.store.list("Cluster")]
        print(json.dumps({"clusters": clusters}))
        return 0

    if not args.bus:
        print("error: this command needs --bus HOST:PORT", file=sys.stderr)
        return 2
    from .utils.codec import to_jsonable

    with RemotePlane(args.bus, args.proxy, token=args.token) as rp:
        if args.command == "get":
            labels = {}
            if args.selector:
                if args.name:
                    # kubectl rejects the combination outright: a selector
                    # on a NAMED get is never applied by any backend
                    print(json.dumps({
                        "error": "--selector and --name are mutually "
                        "exclusive (kubectl semantics)"
                    }))
                    return 2
                for part in args.selector.split(","):
                    k, sep, v = part.partition("=")
                    if not sep:
                        print(json.dumps(
                            {"error": f"bad selector segment {part!r}"}
                        ))
                        return 2
                    labels[k.strip()] = v.strip()
            resp = cmd_get(
                rp, args.gvk, args.namespace, args.name,
                cluster=args.cluster or None, labels=labels or None,
            )
            if resp.error:
                print(json.dumps({"error": resp.error}))
                return 1
            doc = (
                to_jsonable(resp.obj)
                if resp.obj is not None
                else [
                    {"cluster": c, "object": to_jsonable(o)}
                    for c, o in resp.items
                ]
            )
            print(_format_get(doc, args.output, args.gvk))
        elif args.command == "describe":
            print(cmd_describe(rp, args.gvk, args.namespace, args.name))
        elif args.command == "logs":
            for line in cmd_logs(
                rp, args.cluster, args.namespace, args.pod, tail=args.tail
            ):
                print(line)
        elif args.command == "cordon":
            cmd_cordon(rp, args.name)
            print(f"cluster/{args.name} cordoned")
        elif args.command == "uncordon":
            cmd_uncordon(rp, args.name)
            print(f"cluster/{args.name} uncordoned")
        elif args.command == "taint":
            cmd_taint(
                rp, args.name, args.key, args.value, args.effect,
                remove=args.remove,
            )
            print(f"cluster/{args.name} tainted")
        elif args.command == "promote":
            cmd_promote(rp, args.cluster, args.gvk, args.namespace, args.name)
            print(f"{args.gvk} {args.namespace}/{args.name} promoted")
        elif args.command in ("apply", "create"):
            fn = cmd_apply if args.command == "apply" else cmd_create
            try:
                if args.filename == "-":
                    text = sys.stdin.read()
                else:
                    with open(args.filename) as f:
                        text = f.read()
                applied = fn(rp, _load_manifests(text))
            except Exception as exc:  # unreadable file, parse, admission
                print(json.dumps({"error": str(exc)}))
                return 1
            verb = "created" if args.command == "create" else "applied"
            for ref in applied:
                print(f"{ref} {verb}")
        elif args.command == "delete":
            ok = cmd_delete(
                rp, args.kind, args.namespace, args.name, force=args.force
            )
            if not ok:
                print(json.dumps({"error": "not found"}))
                return 1
            print(f"{args.kind}/{args.namespace}/{args.name} deleted")
        elif args.command == "patch":
            try:
                obj = cmd_patch(
                    rp, args.kind, args.namespace, args.name,
                    json.loads(args.patch), args.patch_type,
                )
            except Exception as exc:
                print(json.dumps({"error": str(exc)}))
                return 1
            print(json.dumps(to_jsonable(obj)))
        elif args.command == "edit":
            try:
                obj = cmd_edit(
                    rp, args.kind, args.namespace, args.name,
                    editor=args.editor,
                )
            except Exception as exc:
                print(json.dumps({"error": str(exc)}))
                return 1
            if obj is None:
                print("Edit cancelled, no changes made.")
            else:
                print(json.dumps(to_jsonable(obj)))
        elif args.command in ("label", "annotate"):
            fn = cmd_label if args.command == "label" else cmd_annotate
            try:
                obj = fn(rp, args.kind, args.namespace, args.name, args.changes)
            except Exception as exc:
                print(json.dumps({"error": str(exc)}))
                return 1
            print(json.dumps(to_jsonable(obj)))
        elif args.command == "api-resources":
            print(json.dumps(cmd_api_resources(rp)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
