"""karmadactl-style operations (ref: pkg/karmadactl/karmadactl.go:98-178).

The reference CLI talks to a remote control plane; here every command is a
function over a ControlPlane handle (the in-proc apiserver seam), so the same
operations serve tests, the demo driver, and a future remote transport:

- lifecycle: init (local_up), join / unjoin (push), register / unregister
  (pull), addons
- ops: get / describe / top across clusters (via the search proxy +
  metrics adapter)
- migration: promote (import a member resource as template + policy)
- maintenance: cordon / uncordon, taint
- interpret: dry-run interpreter operations against a template
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from .api.cluster import NO_EXECUTE, NO_SCHEDULE, PULL, Cluster, Taint
from .api.core import ObjectMeta
from .api.policy import (
    ClusterAffinity,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
)
from .controlplane import ControlPlane
from .search import ProxyRequest
from .utils.builders import new_cluster
from .utils.member import MemberCluster

CORDON_TAINT_KEY = "node.karmada.io/unschedulable"  # cordon analogue


def cmd_init(**kw) -> ControlPlane:
    """Bootstrap a control plane (karmadactl init / operator install)."""
    return ControlPlane(**kw)


def cmd_local_up(n_members: int = 3, **kw) -> ControlPlane:
    """hack/local-up-karmada.sh: control plane + n members (last one Pull)."""
    cp = cmd_init(**kw)
    for i in range(1, n_members + 1):
        cluster = new_cluster(f"member{i}", cpu="100", memory="200Gi")
        if i == n_members and n_members >= 3:
            cluster.spec.sync_mode = PULL
        cp.join_cluster(cluster)
    cp.settle()
    return cp


def cmd_join(
    cp: ControlPlane, name: str, member: Optional[MemberCluster] = None, **cluster_kw
) -> Cluster:
    """Push-mode join (pkg/karmadactl/join)."""
    cluster = new_cluster(name, **cluster_kw)
    cp.join_cluster(cluster, member)
    return cluster


def cmd_deinit(cp: ControlPlane) -> None:
    """Tear the control plane down (pkg/karmadactl/cmdinit deinit): unjoin
    every member (draining execution spaces), then drop all control-plane
    state so the instance can be garbage collected."""
    for name in list(cp.members.names()):
        cp.unjoin_cluster(name)
    cp.settle()
    for kind in list(cp.store.kinds()):
        for obj in list(cp.store.list(kind)):
            cp.store.delete(kind, obj.meta.namespaced_name)


def cmd_unjoin(cp: ControlPlane, name: str) -> None:
    cp.unjoin_cluster(name)


def cmd_token_create(cp: ControlPlane) -> str:
    """karmadactl token create: bootstrap token for pull-mode registration."""
    return cp.authority.create_token().token


def cmd_register(
    cp: ControlPlane,
    name: str,
    member: Optional[MemberCluster] = None,
    token: Optional[str] = None,
    **cluster_kw,
) -> Cluster:
    """Pull-mode register (pkg/karmadactl/register): kubeadm-style token ->
    CSR -> signed agent cert, then deploys the agent. Without a token the
    admin-kubeconfig path is used (direct join)."""
    if token is not None:
        record = cp.authority.submit_csr(name, token)
        if record is None:
            raise PermissionError(f"invalid or expired bootstrap token for {name}")
    cluster = new_cluster(name, **cluster_kw)
    cluster.spec.sync_mode = PULL
    cp.join_cluster(cluster, member)
    return cluster


def cmd_unregister(cp: ControlPlane, name: str) -> None:
    cp.unjoin_cluster(name)


def cmd_cordon(cp: ControlPlane, name: str) -> None:
    """Mark a cluster unschedulable (pkg/karmadactl/cordon)."""
    cluster = cp.store.get("Cluster", name)
    if cluster is None:
        raise KeyError(name)
    if not any(t.key == CORDON_TAINT_KEY for t in cluster.spec.taints):
        cluster.spec.taints.append(Taint(key=CORDON_TAINT_KEY, effect=NO_SCHEDULE))
        cp.store.apply(cluster)


def cmd_uncordon(cp: ControlPlane, name: str) -> None:
    cluster = cp.store.get("Cluster", name)
    if cluster is None:
        raise KeyError(name)
    before = len(cluster.spec.taints)
    cluster.spec.taints = [
        t for t in cluster.spec.taints if t.key != CORDON_TAINT_KEY
    ]
    if len(cluster.spec.taints) != before:
        cp.store.apply(cluster)


def cmd_taint(
    cp: ControlPlane, name: str, key: str, value: str = "", effect: str = NO_SCHEDULE,
    remove: bool = False,
) -> None:
    """pkg/karmadactl/cordon taint command analogue."""
    cluster = cp.store.get("Cluster", name)
    if cluster is None:
        raise KeyError(name)
    cluster.spec.taints = [
        t for t in cluster.spec.taints if not (t.key == key and t.effect == effect)
    ]
    if not remove:
        cluster.spec.taints.append(Taint(key=key, value=value, effect=effect))
    cp.store.apply(cluster)


def cmd_get(
    cp: ControlPlane,
    gvk: str,
    namespace: str = "",
    name: str = "",
    cluster: Optional[str] = None,
    labels: Optional[dict] = None,
):
    """Multi-cluster get/list through the proxy chain."""
    verb = "get" if name else "list"
    return cp.proxy.connect(
        ProxyRequest(
            verb=verb, gvk=gvk, namespace=namespace, name=name,
            cluster=cluster, labels=dict(labels or {}),
        )
    )


def cmd_describe(cp: ControlPlane, gvk: str, namespace: str, name: str) -> str:
    """Aggregated description: template + binding + per-cluster status."""
    lines = [f"{gvk} {namespace}/{name}"]
    resp = cmd_get(cp, gvk, namespace, name)
    if resp.obj is None:
        return f"{gvk} {namespace}/{name}: not found"
    kind = gvk.rsplit("/", 1)[-1].lower()
    rb = cp.store.get(
        "ResourceBinding",
        f"{namespace}/{name}-{kind}" if namespace else f"{name}-{kind}",
    )
    if rb is not None:
        lines.append("placements:")
        for tc in rb.spec.clusters:
            lines.append(f"  {tc.name}: {tc.replicas} replicas")
        for item in rb.status.aggregated_status:
            lines.append(
                f"  {item.cluster_name}: applied={item.applied} health={item.health}"
            )
    return "\n".join(lines)


def cmd_top(cp: ControlPlane, workload_key: str):
    """Per-cluster + merged utilization (pkg/karmadactl/top)."""
    if cp.metrics_adapter is None:
        raise RuntimeError(
            "metrics adapter not installed (enable the "
            "karmada-metrics-adapter addon)"
        )
    samples = cp.metrics_adapter.resource_metrics(workload_key)
    merged = cp.metrics_adapter.merged_utilization(workload_key)
    return {"clusters": {s.cluster: s.value for s in samples}, "merged": merged}


def cmd_promote(
    cp: ControlPlane, cluster_name: str, gvk: str, namespace: str, name: str
) -> None:
    """Import an existing member-cluster resource into the control plane as a
    template + policy pinned to that cluster (pkg/karmadactl/promote)."""
    member = cp.members.get(cluster_name)
    if member is None:
        raise KeyError(cluster_name)
    obj = member.get(gvk, namespace, name)
    if obj is None:
        raise KeyError(f"{gvk} {namespace}/{name} not found in {cluster_name}")
    import copy

    template = copy.deepcopy(obj)
    template.meta.resource_version = 0
    cp.store.apply(template)
    api_version, _, kind = gvk.rpartition("/")
    cp.store.apply(
        PropagationPolicy(
            meta=ObjectMeta(name=f"promote-{name}", namespace=namespace),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(
                        api_version=api_version, kind=kind,
                        namespace=namespace, name=name,
                    )
                ],
                placement=Placement(
                    cluster_affinity=ClusterAffinity(cluster_names=[cluster_name])
                ),
                # seamless takeover: adopt the live member object instead of
                # refusing on conflict (promote.go:738-798 sets Overwrite on
                # both the policy and the resource annotation)
                conflict_resolution="Overwrite",
            ),
        )
    )


def cmd_interpret(cp: ControlPlane, template, operation: str, **kw):
    """Dry-run an interpreter operation (pkg/karmadactl/interpret)."""
    interp = cp.interpreter
    if operation == "GetReplicas":
        return interp.get_replicas(template)
    if operation == "ReviseReplica":
        return interp.revise_replica(template, kw["replicas"])
    if operation == "InterpretHealth":
        return interp.interpret_health(template)
    if operation == "ReflectStatus":
        return interp.reflect_status(template)
    if operation == "GetDependencies":
        return interp.get_dependencies(template)
    if operation == "AggregateStatus":
        return interp.aggregate_status(template, kw.get("items", []))
    raise ValueError(f"unknown operation {operation}")


def cmd_logs(
    cp: ControlPlane,
    cluster: str,
    namespace: str,
    pod: str,
    tail: Optional[int] = None,
) -> list[str]:
    """karmadactl logs: pod logs through the clusters/{name}/proxy
    passthrough (pkg/karmadactl/logs)."""
    resp = cp.proxy.connect(
        ProxyRequest(
            verb="logs", gvk="v1/Pod", namespace=namespace, name=pod,
            cluster=cluster, options={"tail": tail},
        )
    )
    if resp.error:
        raise RuntimeError(resp.error)
    return resp.data


def cmd_exec(
    cp: ControlPlane, cluster: str, namespace: str, pod: str, command: list[str]
) -> dict:
    """karmadactl exec: run a command in a member pod via the proxy
    (pkg/karmadactl/exec)."""
    resp = cp.proxy.connect(
        ProxyRequest(
            verb="exec", gvk="v1/Pod", namespace=namespace, name=pod,
            cluster=cluster, options={"command": list(command)},
        )
    )
    if resp.error:
        raise RuntimeError(resp.error)
    return resp.data


def cmd_attach(
    cp: ControlPlane, cluster: str, namespace: str, pod: str
) -> list[str]:
    """karmadactl attach: stream the pod's output (pkg/karmadactl/attach) —
    in-proc this is the log stream from the runtime seam."""
    return cmd_logs(cp, cluster, namespace, pod)


ADDONS = (
    "karmada-descheduler",
    "karmada-scheduler-estimator",
    "karmada-search",
    "karmada-metrics-adapter",
)


def cmd_addons(cp: ControlPlane, enable: Sequence[str] = (), disable: Sequence[str] = ()):
    """Toggle optional components (pkg/karmadactl/addons: estimator,
    descheduler, search, metrics-adapter)."""
    from .controllers import Descheduler
    from .metricsadapter import MetricsAdapter

    state = {}
    for name in enable:
        if name not in ADDONS:
            raise ValueError(f"unknown addon {name}")
        if name == "karmada-descheduler":
            if cp.descheduler is None:
                cp.descheduler = Descheduler(
                    cp.store, cp.runtime, cp.members, clock=cp.clock
                )
            cp.descheduler.active = True
        elif name == "karmada-scheduler-estimator":
            cp.enable_accurate_estimators()
        elif name == "karmada-metrics-adapter" and cp.metrics_adapter is None:
            cp.metrics_adapter = MetricsAdapter(cp.members)
        elif name == "karmada-search":
            cp.search.resync()
        state[name] = "enabled"
    for name in disable:
        if name not in ADDONS:
            raise ValueError(f"unknown addon {name}")
        if name == "karmada-descheduler":
            # the ticker registration is permanent; deactivate in place so
            # disable actually stops reclaim and re-enable can't double-tick
            if cp.descheduler is not None:
                cp.descheduler.active = False
        elif name == "karmada-scheduler-estimator":
            cp.disable_accurate_estimators()
        elif name == "karmada-metrics-adapter":
            cp.metrics_adapter = None
        elif name == "karmada-search":
            cp.search.disable()
        state[name] = "disabled"
    return state


def main(argv: Optional[list[str]] = None) -> int:
    """Thin argparse front end over a fresh local-up plane (demo mode)."""
    parser = argparse.ArgumentParser(prog="karmadactl-tpu")
    sub = parser.add_subparsers(dest="command", required=True)
    lu = sub.add_parser("local-up", help="bootstrap a demo control plane")
    lu.add_argument("--members", type=int, default=3)
    args = parser.parse_args(argv)
    if args.command == "local-up":
        cp = cmd_local_up(args.members)
        clusters = [c.name for c in cp.store.list("Cluster")]
        print(json.dumps({"clusters": clusters}))
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
