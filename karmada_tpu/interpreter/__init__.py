"""Resource interpreter: pluggable semantics for arbitrary resource kinds.

Ref: pkg/resourceinterpreter/interpreter.go:39-143 — eight operations
resolved through a chain of responsibility (customized -> native default).
The reference's customization layers are Lua scripts (declarative CRs +
embedded thirdparty) and webhooks; the TPU build's extension point is
registered Python callables per (kind, operation) — same chain order, no
embedded VM needed in-process.
"""

from .facade import (  # noqa: F401
    AGGREGATE_STATUS,
    GET_DEPENDENCIES,
    GET_REPLICAS,
    INTERPRET_HEALTH,
    REFLECT_STATUS,
    RETAIN,
    REVISE_REPLICA,
    ResourceInterpreter,
)
from .native import register_native_interpreters  # noqa: F401
from .thirdparty import (  # noqa: F401
    THIRDPARTY_CUSTOMIZATIONS,
    register_thirdparty_interpreters,
)


def default_interpreter() -> ResourceInterpreter:
    interp = ResourceInterpreter()
    register_native_interpreters(interp)
    register_thirdparty_interpreters(interp)
    return interp
