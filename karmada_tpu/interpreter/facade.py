"""Interpreter facade: operation registry with customized-over-native chain.

Ref: pkg/resourceinterpreter/interpreter.go:39-143. Operations:
GetReplicas / ReviseReplica / Retain / AggregateStatus / GetDependencies /
ReflectStatus / InterpretHealth (+ HookEnabled). Customized interpreters
(the analogue of declarative-Lua / webhook layers) take precedence over the
native defaults, per kind and operation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..api.core import Resource
from ..api.work import AggregatedStatusItem, ReplicaRequirements

GET_REPLICAS = "GetReplicas"
REVISE_REPLICA = "ReviseReplica"
RETAIN = "Retain"
AGGREGATE_STATUS = "AggregateStatus"
GET_DEPENDENCIES = "GetDependencies"
REFLECT_STATUS = "ReflectStatus"
INTERPRET_HEALTH = "InterpretHealth"

ALL_OPERATIONS = (
    GET_REPLICAS,
    REVISE_REPLICA,
    RETAIN,
    AGGREGATE_STATUS,
    GET_DEPENDENCIES,
    REFLECT_STATUS,
    INTERPRET_HEALTH,
)


@dataclass
class DependentObjectReference:
    """Ref: config/v1alpha1 DependentObjectReference."""

    api_version: str
    kind: str
    namespace: str = ""
    name: str = ""
    label_selector: Optional[dict] = None


class ResourceInterpreter:
    """Chain-of-responsibility interpreter registry.

    Handlers are keyed (gvk, operation) with "*" as the kind wildcard;
    ``register_customized`` layers take precedence over ``register_native``
    (interpreter.go:120-143 chain order, minus the webhook transport)."""

    def __init__(self) -> None:
        self._native: dict[tuple[str, str], Callable] = {}
        self._thirdparty: dict[tuple[str, str], Callable] = {}
        self._webhook: dict[tuple[str, str], Callable] = {}
        self._customized: dict[tuple[str, str], Callable] = {}

    def register_native(self, gvk: str, operation: str, fn: Callable) -> None:
        self._native[(gvk, operation)] = fn

    def register_thirdparty(self, gvk: str, operation: str, fn: Callable) -> None:
        """Built-in customizations for third-party CRDs — override the native
        defaults but yield to user-supplied customizations
        (interpreter.go:120-143: declarative/webhook > thirdparty > native)."""
        self._thirdparty[(gvk, operation)] = fn

    def register_webhook(self, gvk: str, operation: str, fn: Callable) -> None:
        """Remote interpreter webhooks — between in-process customizations
        and the thirdparty corpus (interpreter.go chain order)."""
        self._webhook[(gvk, operation)] = fn

    def deregister_webhook(self, gvk: str, operation: str, fn: Callable = None) -> None:
        """When ``fn`` is given, remove only if it is still the registered
        handler — a stale owner must not clobber a newer registration."""
        if fn is not None and self._webhook.get((gvk, operation)) is not fn:
            return
        self._webhook.pop((gvk, operation), None)

    def register_customized(self, gvk: str, operation: str, fn: Callable) -> None:
        self._customized[(gvk, operation)] = fn

    def deregister_customized(self, gvk: str, operation: str) -> None:
        self._customized.pop((gvk, operation), None)

    def _resolve(self, gvk: str, operation: str) -> Optional[Callable]:
        for table in (self._customized, self._webhook, self._thirdparty, self._native):
            fn = table.get((gvk, operation)) or table.get(("*", operation))
            if fn is not None:
                return fn
        return None

    def hook_enabled(self, gvk: str, operation: str) -> bool:
        return self._resolve(gvk, operation) is not None

    def has_custom_revise(self, gvk: str) -> bool:
        """True when a non-native tier owns ReviseReplica for this kind —
        such hooks may derive arbitrary manifest fields from the replica
        count, so callers must not assume the native replicas-only write."""
        for table in (self._customized, self._webhook, self._thirdparty):
            if (gvk, REVISE_REPLICA) in table or ("*", REVISE_REPLICA) in table:
                return True
        return False

    def revise_patch(
        self, obj: Resource, replicas: int
    ) -> Optional[dict]:
        """Template-delta seam: the top-level spec fields the NATIVE
        ReviseReplica pass would write for this kind, as a patch dict —
        or None when a non-native tier owns the revision (such hooks may
        derive arbitrary fields, so the caller must fall back to full
        rendering). An empty dict means the kind has no revise hook at
        all (the manifest is replica-invariant)."""
        gvk = _gvk(obj)
        if self.has_custom_revise(gvk):
            return None
        fn = self._native.get((gvk, REVISE_REPLICA)) or self._native.get(
            ("*", REVISE_REPLICA)
        )
        if fn is None:
            return {}
        # native._revise_replica semantics, without the clone: Jobs with
        # parallelism revise that field, everything else spec.replicas
        if gvk == "batch/v1/Job" and "parallelism" in obj.spec:
            return {"parallelism": int(replicas)}
        return {"replicas": int(replicas)}

    # -- typed operation wrappers -----------------------------------------

    def get_replicas(self, obj: Resource) -> tuple[int, Optional[ReplicaRequirements]]:
        fn = self._resolve(obj.gvk if hasattr(obj, "gvk") else _gvk(obj), GET_REPLICAS)
        if fn is None:
            return 0, None
        return fn(obj)

    def revise_replica(self, obj: Resource, replicas: int) -> Resource:
        fn = self._resolve(_gvk(obj), REVISE_REPLICA)
        if fn is None:
            return obj
        return fn(obj, replicas)

    def retain(self, desired: Resource, observed: Resource) -> Resource:
        fn = self._resolve(_gvk(desired), RETAIN)
        if fn is None:
            return desired
        return fn(desired, observed)

    def aggregate_status(
        self, obj: Resource, items: list[AggregatedStatusItem]
    ) -> Resource:
        fn = self._resolve(_gvk(obj), AGGREGATE_STATUS)
        if fn is None:
            return obj
        return fn(obj, items)

    def get_dependencies(self, obj: Resource) -> list[DependentObjectReference]:
        fn = self._resolve(_gvk(obj), GET_DEPENDENCIES)
        if fn is None:
            return []
        return fn(obj)

    def reflect_status(self, obj: Resource) -> Optional[dict[str, Any]]:
        fn = self._resolve(_gvk(obj), REFLECT_STATUS)
        if fn is None:
            return obj.status or None
        return fn(obj)

    def interpret_health(self, obj: Resource) -> bool:
        fn = self._resolve(_gvk(obj), INTERPRET_HEALTH)
        if fn is None:
            return True
        return fn(obj)


def _gvk(obj: Resource) -> str:
    return f"{obj.api_version}/{obj.kind}"
