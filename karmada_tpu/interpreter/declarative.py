"""Declarative interpreter customizations: user-defined resource semantics.

Ref: pkg/apis/config/v1alpha1 ResourceInterpreterCustomization +
pkg/resourceinterpreter/customized/declarative (gopher-lua VM pool,
lua.go:46-316) and the configmanager that (de)registers customizations on CR
changes.

The reference embeds Lua; this build's declarative layer is a *path DSL* —
each operation is configured with JSONPath-ish field paths and simple
expressions, which covers the thirdparty customization corpus (replica
fields, status remaps, health predicates) without an embedded VM. Fully
programmatic extensions use ResourceInterpreter.register_customized
(the webhook-interpreter analogue).

DSL fields (all optional, per operation):
- replica_path: dotted path to the replica count (GetReplicas/ReviseReplica)
- requests_path: dotted path to a per-replica resource-request map
- status_paths: list of status fields to reflect (ReflectStatus)
- health: list of {path, op (==|>=|<=), value} predicates, ANDed
  (InterpretHealth)
- status_aggregation: {field: "sum"|"max"|"min"} (AggregateStatus)
- dependencies: list of {kind, api_version, name_path} (GetDependencies)
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Optional

from ..api.core import ObjectMeta, Resource
from ..api.work import AggregatedStatusItem, ReplicaRequirements
from ..utils import DONE, Runtime, Store
from ..utils.quantity import parse_resource_list
from .facade import (
    AGGREGATE_STATUS,
    GET_DEPENDENCIES,
    GET_REPLICAS,
    INTERPRET_HEALTH,
    REFLECT_STATUS,
    REVISE_REPLICA,
    DependentObjectReference,
    ResourceInterpreter,
)


def get_path(obj: Any, path: str) -> Any:
    node = obj
    for part in path.split("."):
        if isinstance(node, dict):
            node = node.get(part)
        elif isinstance(node, list):
            try:
                node = node[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
        if node is None:
            return None
    return node


def set_path(obj: dict, path: str, value: Any) -> None:
    parts = path.split(".")
    node = obj
    for part in parts[:-1]:
        node = node.setdefault(part, {})
    node[parts[-1]] = value


@dataclass
class CustomizationRules:
    replica_path: str = ""
    requests_path: str = ""
    status_paths: list[str] = field(default_factory=list)
    health: list[dict] = field(default_factory=list)
    status_aggregation: dict[str, str] = field(default_factory=dict)
    dependencies: list[dict] = field(default_factory=list)


@dataclass
class ResourceInterpreterCustomization:
    KIND = "ResourceInterpreterCustomization"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    target_api_version: str = ""
    target_kind: str = ""
    rules: CustomizationRules = field(default_factory=CustomizationRules)

    @property
    def target_gvk(self) -> str:
        return f"{self.target_api_version}/{self.target_kind}"


def _compile(rules: CustomizationRules) -> dict[str, Any]:
    """Build operation callables from the DSL."""
    ops: dict[str, Any] = {}
    if rules.replica_path:

        def get_replicas(obj: Resource):
            replicas = int(get_path(obj.spec, rules.replica_path) or 0)
            reqs = None
            if rules.requests_path:
                raw = get_path(obj.spec, rules.requests_path) or {}
                reqs = ReplicaRequirements(
                    resource_request=parse_resource_list(raw),
                    namespace=obj.meta.namespace,
                )
            return replicas, reqs

        def revise_replica(obj: Resource, replicas: int):
            out = copy.deepcopy(obj)
            set_path(out.spec, rules.replica_path, replicas)
            return out

        ops[GET_REPLICAS] = get_replicas
        ops[REVISE_REPLICA] = revise_replica
    if rules.status_paths:

        def reflect_status(obj: Resource):
            if not obj.status:
                return None
            return {
                p: get_path(obj.status, p)
                for p in rules.status_paths
                if get_path(obj.status, p) is not None
            }

        ops[REFLECT_STATUS] = reflect_status
    if rules.health:

        def interpret_health(obj: Resource) -> bool:
            st = obj.status or {}
            for pred in rules.health:
                value = get_path(st, pred["path"])
                want = pred.get("value")
                op = pred.get("op", "==")
                if value is None:
                    return False
                if op == "==" and value != want:
                    return False
                if op == ">=" and not value >= want:
                    return False
                if op == "<=" and not value <= want:
                    return False
            return True

        ops[INTERPRET_HEALTH] = interpret_health
    if rules.status_aggregation:

        def aggregate_status(obj: Resource, items: list[AggregatedStatusItem]):
            out = copy.deepcopy(obj)
            agg: dict[str, Any] = {}
            for fname, how in rules.status_aggregation.items():
                values = [
                    (item.status or {}).get(fname)
                    for item in items
                    if (item.status or {}).get(fname) is not None
                ]
                if not values:
                    continue
                if how == "sum":
                    agg[fname] = sum(values)
                elif how == "max":
                    agg[fname] = max(values)
                elif how == "min":
                    agg[fname] = min(values)
            out.status = {**(out.status or {}), **agg}
            return out

        ops[AGGREGATE_STATUS] = aggregate_status
    if rules.dependencies:

        def get_dependencies(obj: Resource):
            deps = []
            for rule in rules.dependencies:
                name = get_path(obj.spec, rule.get("name_path", ""))
                if name:
                    deps.append(
                        DependentObjectReference(
                            api_version=rule.get("api_version", "v1"),
                            kind=rule.get("kind", "ConfigMap"),
                            namespace=obj.meta.namespace,
                            name=str(name),
                        )
                    )
            return deps

        ops[GET_DEPENDENCIES] = get_dependencies
    return ops


class CustomizationConfigManager:
    """Registers/deregisters customizations on CR events
    (customized/declarative configmanager analogue)."""

    def __init__(
        self, store: Store, runtime: Runtime, interpreter: ResourceInterpreter
    ) -> None:
        self.store = store
        self.interpreter = interpreter
        self._registered: dict[str, list[tuple[str, str]]] = {}
        self.worker = runtime.new_worker("interpreter-config", self._reconcile)
        store.watch(
            "ResourceInterpreterCustomization",
            lambda e: self.worker.enqueue((e.key, e.type)),
        )

    def _reconcile(self, key_type) -> Optional[str]:
        key, event_type = key_type
        cr = self.store.get("ResourceInterpreterCustomization", key)
        # drop previous registrations for this CR
        previous = self._registered.pop(key, [])
        for gvk, op in previous:
            self.interpreter.deregister_customized(gvk, op)
        affected_gvks = {gvk for gvk, _ in previous}
        if cr is not None:
            ops = _compile(cr.rules)
            regs = []
            for op, fn in ops.items():
                self.interpreter.register_customized(cr.target_gvk, op, fn)
                regs.append((cr.target_gvk, op))
            self._registered[key] = regs
            affected_gvks.add(cr.target_gvk)
        # full re-sync of affected templates (the reference's controllers
        # resync on interpreter-config changes): a touch re-runs the
        # detector/binding pipeline with the new semantics
        for res in self.store.list("Resource"):
            if f"{res.api_version}/{res.kind}" in affected_gvks:
                self.store.apply(res)
        return DONE
