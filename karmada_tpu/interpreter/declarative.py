"""Declarative interpreter customizations: user-defined resource semantics.

Ref: pkg/apis/config/v1alpha1 ResourceInterpreterCustomization +
pkg/resourceinterpreter/customized/declarative (gopher-lua VM pool,
lua.go:46-316) and the configmanager that (de)registers customizations on CR
changes.

The reference embeds Lua; this build's declarative layer is a *path DSL* —
each operation is configured with JSONPath-ish field paths and simple
expressions, which covers the thirdparty customization corpus (replica
fields, status remaps, health predicates) without an embedded VM. Fully
programmatic extensions use ResourceInterpreter.register_customized
(the webhook-interpreter analogue).

DSL fields (all optional, per operation):
- replica_path: dotted path to the replica count (GetReplicas/ReviseReplica)
- replica_default: replica count when replica_path is unset on the object
  (argo Workflow/BroadcastJob default to 1 when .spec.parallelism is nil)
- requests_path: dotted path to a per-replica resource-request map
- pod_requests_path: dotted spec path of a pod template whose container
  requests form the per-replica requirements (kube.accuratePodRequirements)
- status_paths: list of status fields to reflect (ReflectStatus)
- health: predicate list, ANDed (InterpretHealth). Forms:
    {path, op (==|!=|>=|<=|in|exists), value}        — direct status field
    {path, op, spec_path} / {path, op, status_path}  — compare two fields
    {condition: type, status: "True", reason: r?}    — scan status.conditions
    {observed_generation: true}                      — status.observedGeneration
                                                       == metadata.generation
    {any: [sub-predicates]}                          — OR group
- status_aggregation: {field: "sum"|"max"|"min"|"last"|"and"|"or"}
  ("last" = last non-empty, for revisions/selectors)
- status_zero_fields: numeric fields zero-filled when no member statuses
- aggregate_observed_generation: set status.observedGeneration to
  metadata.generation once every member has observed its own generation
- retain_paths: spec paths copied observed→desired (Retain; flux
  spec.suspend carry-over pattern)
- retain_status: carry the whole observed status into desired (argo)
- dependencies: list of (GetDependencies):
    {kind, api_version, name_path, namespace_path?}  — single ref
    {list_path, name_field, kind | kind_field, api_version} — ref list
    {pod_template_path}  — walk a pod template for configmaps/secrets/
                           PVCs/serviceaccounts (kube.getPodDependencies)
"""

from __future__ import annotations

from ..utils.clone import clone_json, clone_resource
from dataclasses import dataclass, field
from typing import Any, Optional

from ..api.core import ObjectMeta, Resource
from ..api.work import AggregatedStatusItem, ReplicaRequirements
from ..utils import DONE, Runtime, Store
from ..utils.quantity import parse_resource_list
from .facade import (
    AGGREGATE_STATUS,
    GET_DEPENDENCIES,
    GET_REPLICAS,
    INTERPRET_HEALTH,
    REFLECT_STATUS,
    RETAIN,
    REVISE_REPLICA,
    DependentObjectReference,
    ResourceInterpreter,
)


def get_path(obj: Any, path: str) -> Any:
    node = obj
    for part in path.split("."):
        if isinstance(node, dict):
            node = node.get(part)
        elif isinstance(node, list):
            try:
                node = node[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
        if node is None:
            return None
    return node


def set_path(obj: dict, path: str, value: Any) -> None:
    parts = path.split(".")
    node = obj
    for part in parts[:-1]:
        node = node.setdefault(part, {})
    node[parts[-1]] = value


@dataclass
class CustomizationRules:
    replica_path: str = ""
    replica_default: int = 0
    requests_path: str = ""
    pod_requests_path: str = ""
    status_paths: list[str] = field(default_factory=list)
    health: list[dict] = field(default_factory=list)
    status_aggregation: dict[str, str] = field(default_factory=dict)
    status_zero_fields: list[str] = field(default_factory=list)
    aggregate_observed_generation: bool = False
    retain_paths: list[str] = field(default_factory=list)
    retain_status: bool = False
    dependencies: list[dict] = field(default_factory=list)
    # --- expression tier (mirrors the reference CR's luaScript slots,
    # config/v1alpha1 CustomizationTarget: replicaResource/replicaRevision/
    # retention/statusAggregation/healthInterpretation/statusReflection/
    # dependencyInterpretation). A script field, when set, OVERRIDES the
    # path-DSL for that operation; syntax is the sandboxed expression
    # language of interpreter/exprlang.py with the same entry-point names
    # the reference's Lua VM dispatches to (lua.go:46-316).
    replica_resource_script: str = ""  # GetReplicas(observedObj)
    replica_revision_script: str = ""  # ReviseReplica(desiredObj, replica)
    retention_script: str = ""  # Retain(desiredObj, observedObj)
    status_aggregation_script: str = ""  # AggregateStatus(desiredObj, items)
    health_script: str = ""  # InterpretHealth(observedObj)
    status_reflection_script: str = ""  # ReflectStatus(observedObj)
    dependency_script: str = ""  # GetDependencies(desiredObj)


@dataclass
class ResourceInterpreterCustomization:
    KIND = "ResourceInterpreterCustomization"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    target_api_version: str = ""
    target_kind: str = ""
    rules: CustomizationRules = field(default_factory=CustomizationRules)

    @property
    def target_gvk(self) -> str:
        return f"{self.target_api_version}/{self.target_kind}"


def _check_predicate(pred: dict, obj: Resource) -> bool:
    st = obj.status or {}
    if "any" in pred:
        return any(_check_predicate(p, obj) for p in pred["any"])
    if pred.get("observed_generation"):
        gen = obj.meta.generation if hasattr(obj.meta, "generation") else 0
        return (st.get("observedGeneration") or 0) >= (gen or 0)
    if "condition" in pred:
        for cond in st.get("conditions") or []:
            if cond.get("type") != pred["condition"]:
                continue
            if cond.get("status") != pred.get("status", "True"):
                continue
            if "reason" in pred and cond.get("reason") != pred["reason"]:
                continue
            return True
        return False
    value = get_path(st, pred["path"])
    op = pred.get("op", "==")
    if op == "exists":
        return value is not None
    if "spec_path" in pred:
        want = get_path(obj.spec, pred["spec_path"])
    elif "status_path" in pred:
        want = get_path(st, pred["status_path"])
    else:
        want = pred.get("value")
    if value is None:
        return False
    if op == "==":
        return value == want
    if op == "!=":
        return value != want
    if op == "in":
        return value in (want or [])
    if op == ">=":
        return value >= want
    if op == "<=":
        return value <= want
    return False


def _compile(rules: CustomizationRules) -> dict[str, Any]:
    """Build operation callables from the DSL."""
    ops: dict[str, Any] = {}
    if rules.replica_path or rules.replica_default:

        def get_replicas(obj: Resource):
            raw_replicas = (
                get_path(obj.spec, rules.replica_path) if rules.replica_path else None
            )
            try:
                replicas = int(raw_replicas)
            except (TypeError, ValueError):
                # unset, or an IntOrString like "50%" (kruise BroadcastJob
                # parallelism) — fall back rather than wedge the reconciler
                replicas = rules.replica_default

            reqs = None
            if rules.requests_path:
                raw = get_path(obj.spec, rules.requests_path) or {}
                reqs = ReplicaRequirements(
                    resource_request=parse_resource_list(raw),
                    namespace=obj.meta.namespace,
                )
            elif rules.pod_requests_path:
                template = get_path(obj.spec, rules.pod_requests_path) or {}
                from .native import pod_requests

                reqs = ReplicaRequirements(
                    resource_request=pod_requests(template.get("spec") or {}),
                    namespace=obj.meta.namespace,
                )
            return replicas, reqs

        ops[GET_REPLICAS] = get_replicas
        if rules.replica_path:

            def revise_replica(obj: Resource, replicas: int):
                out = clone_resource(obj)
                set_path(out.spec, rules.replica_path, replicas)
                return out

            ops[REVISE_REPLICA] = revise_replica
    if rules.status_paths:

        def reflect_status(obj: Resource):
            out: dict[str, Any] = {}
            for p in rules.status_paths:
                if p.startswith("meta."):
                    # metadata projected into the reflected status (e.g.
                    # meta.generation -> status["generation"], so aggregation
                    # can compare member generation vs observedGeneration)
                    value = getattr(obj.meta, p[len("meta."):], None)
                else:
                    value = get_path(obj.status or {}, p)
                if value is not None:
                    out[p.split(".", 1)[-1] if p.startswith("meta.") else p] = value
            return out or None

        ops[REFLECT_STATUS] = reflect_status
    if rules.health:

        def interpret_health(obj: Resource) -> bool:
            return all(_check_predicate(p, obj) for p in rules.health)

        ops[INTERPRET_HEALTH] = interpret_health
    if (
        rules.status_aggregation
        or rules.status_zero_fields
        or rules.aggregate_observed_generation
    ):

        def aggregate_status(obj: Resource, items: list[AggregatedStatusItem]):
            out = clone_resource(obj)
            agg: dict[str, Any] = {}
            for fname, how in rules.status_aggregation.items():
                values = [
                    (item.status or {}).get(fname)
                    for item in items
                    if (item.status or {}).get(fname) not in (None, "")
                ]
                if not values:
                    if fname in rules.status_zero_fields:
                        agg[fname] = 0
                    continue
                if how == "sum":
                    agg[fname] = sum(values)
                elif how == "max":
                    agg[fname] = max(values)
                elif how == "min":
                    agg[fname] = min(values)
                elif how == "last":
                    agg[fname] = values[-1]
                elif how == "and":
                    agg[fname] = all(values)
                elif how == "or":
                    agg[fname] = any(values)
            if rules.aggregate_observed_generation:
                # advance only once every member observed its own generation
                all_observed = all(
                    (item.status or {}).get("observedGeneration", 0)
                    >= (item.status or {}).get("generation", 0)
                    for item in items
                )
                if all_observed:
                    agg["observedGeneration"] = out.meta.generation or 0
            out.status = {**(out.status or {}), **agg}
            return out

        ops[AGGREGATE_STATUS] = aggregate_status
    if rules.retain_paths or rules.retain_status:

        def retain(desired: Resource, observed: Resource):
            out = clone_resource(desired)
            for path in rules.retain_paths:
                value = get_path(observed.spec, path)
                if value is not None:
                    set_path(out.spec, path, clone_json(value))
            if rules.retain_status and observed.status is not None:
                out.status = clone_json(observed.status)
            return out

        ops[RETAIN] = retain
    if rules.dependencies:

        def get_dependencies(obj: Resource):
            deps = []
            for rule in rules.dependencies:
                if rule.get("pod_template_path"):
                    template = get_path(obj.spec, rule["pod_template_path"]) or {}
                    from .native import pod_spec_dependencies

                    deps.extend(
                        pod_spec_dependencies(
                            template.get("spec") or {}, obj.meta.namespace
                        )
                    )
                elif rule.get("list_path"):
                    for entry in get_path(obj.spec, rule["list_path"]) or []:
                        if not isinstance(entry, dict):
                            continue
                        name = entry.get(rule.get("name_field", "name"))
                        kind = (
                            entry.get(rule["kind_field"])
                            if rule.get("kind_field")
                            else rule.get("kind", "ConfigMap")
                        )
                        if name and kind:
                            deps.append(
                                DependentObjectReference(
                                    api_version=rule.get("api_version", "v1"),
                                    kind=str(kind),
                                    namespace=obj.meta.namespace,
                                    name=str(name),
                                )
                            )
                else:
                    name = get_path(obj.spec, rule.get("name_path", ""))
                    if name:
                        namespace = (
                            get_path(obj.spec, rule["namespace_path"])
                            if rule.get("namespace_path")
                            else None
                        )
                        # the referenced kind may live in the object itself
                        # (flux sourceRef.kind), with a per-kind api group
                        kind = (
                            get_path(obj.spec, rule["kind_path"])
                            if rule.get("kind_path")
                            else None
                        ) or rule.get("kind", "ConfigMap")
                        api_version = rule.get("api_version_by_kind", {}).get(
                            kind, rule.get("api_version", "v1")
                        )
                        deps.append(
                            DependentObjectReference(
                                api_version=api_version,
                                kind=str(kind),
                                namespace=str(namespace or obj.meta.namespace),
                                name=str(name),
                            )
                        )
            return deps

        ops[GET_DEPENDENCIES] = get_dependencies
    _compile_scripts(rules, ops)
    return ops


def _compile_scripts(rules: CustomizationRules, ops: dict[str, Any]) -> None:
    """Overlay the expression-tier scripts (exprlang) onto the op map —
    scripts override the path-DSL for their operation. Entry-point names
    and call shapes mirror the reference Lua VM (luavm/lua.go:46-316)."""
    from .exprlang import ExprVM
    from .webhook import resource_from_dict, resource_to_dict

    def vm_for(source: str) -> ExprVM:
        return ExprVM(source)  # raises ScriptError on invalid scripts

    if rules.replica_resource_script:
        vm = vm_for(rules.replica_resource_script)

        def get_replicas_script(obj: Resource, vm=vm):
            out = vm.call("GetReplicas", resource_to_dict(obj))
            if isinstance(out, tuple):
                replicas, requires = (list(out) + [None])[:2]
            else:
                replicas, requires = out, None
            reqs = None
            if isinstance(requires, dict):
                claim = requires.get("nodeClaim") or {}
                from ..api.work import NodeClaim

                reqs = ReplicaRequirements(
                    resource_request=parse_resource_list(
                        requires.get("resourceRequest") or {}
                    ),
                    node_claim=(
                        NodeClaim(
                            node_selector=claim.get("nodeSelector") or {},
                            tolerations=claim.get("tolerations") or [],
                            hard_node_affinity=claim.get("hardNodeAffinity"),
                        )
                        if claim
                        else None
                    ),
                    namespace=str(requires.get("namespace") or obj.meta.namespace),
                    priority_class_name=str(
                        requires.get("priorityClassName") or ""
                    ),
                )
            return int(replicas or 0), reqs

        ops[GET_REPLICAS] = get_replicas_script
    if rules.replica_revision_script:
        vm = vm_for(rules.replica_revision_script)

        def revise_replica_script(obj: Resource, replicas: int, vm=vm):
            out = vm.call("ReviseReplica", resource_to_dict(obj), replicas)
            return resource_from_dict(out)

        ops[REVISE_REPLICA] = revise_replica_script
    if rules.retention_script:
        vm = vm_for(rules.retention_script)

        def retain_script(desired: Resource, observed: Resource, vm=vm):
            out = vm.call(
                "Retain", resource_to_dict(desired), resource_to_dict(observed)
            )
            return resource_from_dict(out)

        ops[RETAIN] = retain_script
    if rules.status_aggregation_script:
        vm = vm_for(rules.status_aggregation_script)

        def aggregate_script(obj: Resource, items: list[AggregatedStatusItem], vm=vm):
            wire_items = [
                {
                    "clusterName": it.cluster_name,
                    "status": it.status,
                    "applied": it.applied,
                    "health": it.health,
                }
                for it in items
            ]
            out = vm.call(
                "AggregateStatus", resource_to_dict(obj), wire_items
            )
            return resource_from_dict(out)

        ops[AGGREGATE_STATUS] = aggregate_script
    if rules.health_script:
        vm = vm_for(rules.health_script)

        def health_script(obj: Resource, vm=vm) -> bool:
            return bool(vm.call("InterpretHealth", resource_to_dict(obj)))

        ops[INTERPRET_HEALTH] = health_script
    if rules.status_reflection_script:
        vm = vm_for(rules.status_reflection_script)

        def reflect_script(obj: Resource, vm=vm):
            out = vm.call("ReflectStatus", resource_to_dict(obj))
            return out if out else None

        ops[REFLECT_STATUS] = reflect_script
    if rules.dependency_script:
        vm = vm_for(rules.dependency_script)

        def dependencies_script(obj: Resource, vm=vm):
            out = vm.call("GetDependencies", resource_to_dict(obj)) or []
            return [
                DependentObjectReference(
                    api_version=str(d.get("apiVersion", "v1")),
                    kind=str(d.get("kind", "")),
                    namespace=str(d.get("namespace") or obj.meta.namespace),
                    name=str(d.get("name", "")),
                )
                for d in out
                if isinstance(d, dict)
            ]

        ops[GET_DEPENDENCIES] = dependencies_script


class CustomizationConfigManager:
    """Registers/deregisters customizations on CR events
    (customized/declarative configmanager analogue)."""

    def __init__(
        self, store: Store, runtime: Runtime, interpreter: ResourceInterpreter
    ) -> None:
        self.store = store
        self.interpreter = interpreter
        self._registered: dict[str, list[tuple[str, str]]] = {}
        self.worker = runtime.new_worker("interpreter-config", self._reconcile)
        store.watch(
            "ResourceInterpreterCustomization",
            lambda e: self.worker.enqueue((e.key, e.type)),
        )

    def _reconcile(self, key_type) -> Optional[str]:
        key, event_type = key_type
        cr = self.store.get("ResourceInterpreterCustomization", key)
        # drop previous registrations for this CR
        previous = self._registered.pop(key, [])
        for gvk, op in previous:
            self.interpreter.deregister_customized(gvk, op)
        affected_gvks = {gvk for gvk, _ in previous}
        if cr is not None:
            ops = _compile(cr.rules)
            regs = []
            for op, fn in ops.items():
                self.interpreter.register_customized(cr.target_gvk, op, fn)
                regs.append((cr.target_gvk, op))
            self._registered[key] = regs
            affected_gvks.add(cr.target_gvk)
        # full re-sync of affected templates (the reference's controllers
        # resync on interpreter-config changes): a touch re-runs the
        # detector/binding pipeline with the new semantics
        for res in self.store.list("Resource"):
            if f"{res.api_version}/{res.kind}" in affected_gvks:
                self.store.apply(res)
        return DONE
