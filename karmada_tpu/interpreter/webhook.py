"""Interpreter webhooks: HTTPS extension transport for resource semantics.

Ref: pkg/apis/config/v1alpha1/resourceinterpreterwebhook_types.go
(ResourceInterpreterWebhookConfiguration: clientConfig + RuleWithOperations
+ timeoutSeconds) and interpretercontext_types.go:42-133
(ResourceInterpreterContext request/response: uid, kind, operation, object,
observedObject, replicas, aggregatedStatus → successful, JSONPatch,
replicas/requirements, dependencies, rawStatus, healthy);
pkg/resourceinterpreter/customized/webhook (client + configmanager).

Shape: an extension author runs ``InterpreterWebhookServer`` hosting plain
Python operation handlers behind HTTP(S); the control plane's
``WebhookConfigManager`` watches ``ResourceInterpreterWebhookConfiguration``
objects and registers a ``WebhookInterpreterClient`` per matching
(kind, operation) on the facade's webhook tier — above the embedded
thirdparty corpus, below user in-process customizations (the reference's
chain order, interpreter.go:120-143). Responses patch via RFC 6902
JSONPatch, same as the reference (we apply add/replace/remove).
"""

from __future__ import annotations

from ..utils.clone import clone_json
import json
import ssl
import threading
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional

from ..api.core import ObjectMeta, Resource, new_uid
from ..api.work import AggregatedStatusItem, NodeClaim, ReplicaRequirements
from ..utils import DONE, Runtime, Store
from .facade import (
    AGGREGATE_STATUS,
    GET_DEPENDENCIES,
    GET_REPLICAS,
    INTERPRET_HEALTH,
    REFLECT_STATUS,
    RETAIN,
    REVISE_REPLICA,
    DependentObjectReference,
    ResourceInterpreter,
)

# ---------------------------------------------------------------------------
# wire (de)serialization


def resource_to_dict(obj: Resource) -> dict:
    return {
        "apiVersion": obj.api_version,
        "kind": obj.kind,
        "metadata": {
            "name": obj.meta.name,
            "namespace": obj.meta.namespace,
            "labels": dict(obj.meta.labels),
            "annotations": dict(obj.meta.annotations),
            "generation": obj.meta.generation,
        },
        "spec": clone_json(obj.spec),
        "status": clone_json(obj.status),
    }


def resource_from_dict(d: dict) -> Resource:
    meta = d.get("metadata") or {}
    return Resource(
        api_version=d.get("apiVersion", ""),
        kind=d.get("kind", ""),
        meta=ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", ""),
            labels=dict(meta.get("labels") or {}),
            annotations=dict(meta.get("annotations") or {}),
            generation=int(meta.get("generation") or 0),
        ),
        spec=d.get("spec") or {},
        status=d.get("status") or {},
    )


def apply_json_patch(doc: dict, patch: list[dict]) -> dict:
    """RFC 6902 add/replace/remove over a JSON document (the subset the
    reference consumes for interpreter responses)."""
    out = clone_json(doc)
    for op in patch:
        path = op.get("path", "")
        parts = [p.replace("~1", "/").replace("~0", "~") for p in path.split("/")[1:]]
        parent = out
        for raw in parts[:-1]:
            key = int(raw) if isinstance(parent, list) else raw
            parent = parent[key]
        last = parts[-1] if parts else ""
        kind = op.get("op")
        if kind in ("add", "replace"):
            if isinstance(parent, list):
                if last == "-":
                    parent.append(op.get("value"))
                elif kind == "add":
                    parent.insert(int(last), op.get("value"))
                else:
                    parent[int(last)] = op.get("value")
            else:
                parent[last] = op.get("value")
        elif kind == "remove":
            if isinstance(parent, list):
                del parent[int(last)]
            else:
                parent.pop(last, None)
        else:
            raise ValueError(f"unsupported JSONPatch op {kind!r}")
    return out


# ---------------------------------------------------------------------------
# configuration API (config/v1alpha1)


@dataclass
class RuleWithOperations:
    """Operations × apiVersions × kinds; '*' wildcards."""

    operations: list[str] = field(default_factory=lambda: ["*"])
    api_versions: list[str] = field(default_factory=lambda: ["*"])
    kinds: list[str] = field(default_factory=lambda: ["*"])

    def matches_target(self, api_version: str, kind: str) -> bool:
        return ("*" in self.api_versions or api_version in self.api_versions) and (
            "*" in self.kinds or kind in self.kinds
        )

    def matches_operation(self, operation: str) -> bool:
        return "*" in self.operations or operation in self.operations


@dataclass
class WebhookClientConfig:
    url: str = ""
    ca_bundle: Optional[bytes] = None


@dataclass
class InterpreterWebhook:
    name: str = ""
    client_config: WebhookClientConfig = field(default_factory=WebhookClientConfig)
    rules: list[RuleWithOperations] = field(default_factory=list)
    timeout_seconds: float = 10.0


@dataclass
class ResourceInterpreterWebhookConfiguration:
    KIND = "ResourceInterpreterWebhookConfiguration"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    webhooks: list[InterpreterWebhook] = field(default_factory=list)


# ---------------------------------------------------------------------------
# server side (extension author)


class InterpreterWebhookServer:
    """Hosts operation handlers behind HTTP(S).

    ``handlers`` maps operation name → callable taking the decoded request
    dict and returning response fields (dict). Convenience: ``from_rules``
    builds handlers straight from declarative-style callables."""

    def __init__(
        self,
        handlers: dict[str, Callable[[dict], dict]],
        address: tuple[str, int] = ("127.0.0.1", 0),
        *,
        certfile: Optional[str] = None,
        keyfile: Optional[str] = None,
    ):
        self.handlers = dict(handlers)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                request = body.get("request") or {}
                uid = request.get("uid", "")
                op = request.get("operation", "")
                fn = outer.handlers.get(op)
                if fn is None:
                    response = {
                        "uid": uid,
                        "successful": False,
                        "status": {"message": f"operation {op} not supported"},
                    }
                else:
                    try:
                        fields = fn(request)
                        response = {"uid": uid, "successful": True, **fields}
                    except Exception as exc:  # surfaced to the caller
                        response = {
                            "uid": uid,
                            "successful": False,
                            "status": {"message": str(exc)},
                        }
                payload = json.dumps({"response": response}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._httpd = ThreadingHTTPServer(address, Handler)
        self.scheme = "http"
        if certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile)
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket, server_side=True)
            self.scheme = "https"
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"{self.scheme}://127.0.0.1:{self.port}/interpret"

    def start(self) -> str:
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self.url

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


# ---------------------------------------------------------------------------
# client side (control plane)


class WebhookInterpreterClient:
    """POSTs ResourceInterpreterContext requests to one webhook endpoint and
    maps responses back to facade operations (customized/webhook client)."""

    def __init__(self, webhook: InterpreterWebhook):
        self.webhook = webhook
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if webhook.client_config.ca_bundle:
            # full verification including hostname — a CA-signed cert for a
            # different host must not be accepted
            self._ssl_ctx = ssl.create_default_context(
                cadata=webhook.client_config.ca_bundle.decode()
            )

    def _call(self, request_fields: dict) -> dict:
        request = {"uid": new_uid(), **request_fields}
        body = json.dumps({"request": request}).encode()
        req = urllib.request.Request(
            self.webhook.client_config.url,
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(
            req, timeout=self.webhook.timeout_seconds, context=self._ssl_ctx
        ) as resp:
            payload = json.loads(resp.read())
        response = payload.get("response") or {}
        if response.get("uid") != request["uid"]:
            raise RuntimeError("webhook response uid mismatch")
        if not response.get("successful"):
            message = (response.get("status") or {}).get("message", "")
            raise RuntimeError(f"webhook {self.webhook.name} failed: {message}")
        return response

    def _base(self, obj: Resource, operation: str) -> dict:
        return {
            "kind": {"apiVersion": obj.api_version, "kind": obj.kind},
            "name": obj.meta.name,
            "namespace": obj.meta.namespace,
            "operation": operation,
            "object": resource_to_dict(obj),
        }

    def _patched(self, obj: Resource, response: dict) -> Resource:
        patch = response.get("patch")
        if not patch:
            return obj
        if isinstance(patch, str):
            patch = json.loads(patch)
        return resource_from_dict(apply_json_patch(resource_to_dict(obj), patch))

    # -- facade operations --------------------------------------------------

    def get_replicas(self, obj: Resource):
        response = self._call(self._base(obj, "InterpretReplica"))
        requirements = None
        raw = response.get("replicaRequirements")
        if raw:
            from ..utils.quantity import parse_resource_list

            claim = raw.get("nodeClaim") or None
            requirements = ReplicaRequirements(
                # the wire carries ResourceList quantity strings ("500m",
                # "1Gi") or plain ints — parse, don't cast
                resource_request=parse_resource_list(raw.get("resourceRequest") or {}),
                node_claim=NodeClaim(
                    node_selector=dict(claim.get("nodeSelector") or {}),
                    tolerations=list(claim.get("tolerations") or []),
                )
                if claim
                else None,
                namespace=obj.meta.namespace,
                priority_class_name=raw.get("priorityClassName", ""),
            )
        return int(response.get("replicas") or 0), requirements

    def revise_replica(self, obj: Resource, replicas: int) -> Resource:
        response = self._call(
            {**self._base(obj, "ReviseReplica"), "replicas": int(replicas)}
        )
        return self._patched(obj, response)

    def retain(self, desired: Resource, observed: Resource) -> Resource:
        response = self._call(
            {
                **self._base(desired, "Retain"),
                "observedObject": resource_to_dict(observed),
            }
        )
        return self._patched(desired, response)

    def aggregate_status(
        self, obj: Resource, items: list[AggregatedStatusItem]
    ) -> Resource:
        response = self._call(
            {
                **self._base(obj, "AggregateStatus"),
                "aggregatedStatus": [
                    {
                        "clusterName": i.cluster_name,
                        "status": i.status,
                        "applied": i.applied,
                        "health": i.health,
                    }
                    for i in items
                ],
            }
        )
        return self._patched(obj, response)

    def get_dependencies(self, obj: Resource) -> list[DependentObjectReference]:
        response = self._call(self._base(obj, "InterpretDependency"))
        return [
            DependentObjectReference(
                api_version=d.get("apiVersion", "v1"),
                kind=d.get("kind", ""),
                namespace=d.get("namespace", obj.meta.namespace),
                name=d.get("name", ""),
            )
            for d in response.get("dependencies") or []
        ]

    def reflect_status(self, obj: Resource) -> Optional[dict]:
        response = self._call(self._base(obj, "InterpretStatus"))
        return response.get("rawStatus")

    def interpret_health(self, obj: Resource) -> bool:
        response = self._call(self._base(obj, "InterpretHealth"))
        return bool(response.get("healthy"))


# operation name on the wire (reference InterpreterOperation) → facade op +
# client method
_WIRE_OPS = {
    GET_REPLICAS: ("InterpretReplica", "get_replicas"),
    REVISE_REPLICA: ("ReviseReplica", "revise_replica"),
    RETAIN: ("Retain", "retain"),
    AGGREGATE_STATUS: ("AggregateStatus", "aggregate_status"),
    GET_DEPENDENCIES: ("InterpretDependency", "get_dependencies"),
    REFLECT_STATUS: ("InterpretStatus", "reflect_status"),
    INTERPRET_HEALTH: ("InterpretHealth", "interpret_health"),
}


class WebhookConfigManager:
    """Watches ResourceInterpreterWebhookConfiguration and (de)registers
    webhook clients on the facade's webhook tier (customized/webhook
    configmanager analogue)."""

    def __init__(
        self, store: Store, runtime: Runtime, interpreter: ResourceInterpreter
    ) -> None:
        self.store = store
        self.interpreter = interpreter
        self._registered: dict[str, list[tuple[str, str]]] = {}
        self.worker = runtime.new_worker("interpreter-webhook-config", self._reconcile)
        store.watch(
            ResourceInterpreterWebhookConfiguration.KIND,
            lambda e: self.worker.enqueue(e.key),
        )
        # wildcard rules bind per-GVK at reconcile time; a template kind
        # appearing later must re-resolve every configuration
        self._seen_gvks: set[str] = set()
        store.watch("Resource", self._on_resource)

    def _on_resource(self, event) -> None:
        obj = event.obj
        if obj is None:
            return
        gvk = f"{obj.api_version}/{obj.kind}"
        if gvk in self._seen_gvks:
            return
        self._seen_gvks.add(gvk)
        for config in self.store.list(ResourceInterpreterWebhookConfiguration.KIND):
            self.worker.enqueue(config.meta.namespaced_name)

    def _known_gvks(self) -> set[str]:
        """Kinds currently in the store that a wildcard rule could serve."""
        return {f"{r.api_version}/{r.kind}" for r in self.store.list("Resource")}

    def _reconcile(self, key: str) -> Optional[str]:
        config = self.store.get(ResourceInterpreterWebhookConfiguration.KIND, key)
        previous = self._registered.pop(key, [])
        for gvk, op, fn in previous:
            # identity-guarded: an overlapping config that registered later
            # owns the slot now and must not be clobbered
            self.interpreter.deregister_webhook(gvk, op, fn)
        affected_gvks = {gvk for gvk, _, _ in previous}
        if config is None:
            self._resync(affected_gvks)
            return DONE
        regs: list[tuple[str, str, object]] = []
        for webhook in config.webhooks:
            client = WebhookInterpreterClient(webhook)
            for rule in webhook.rules:
                kinds = rule.kinds
                versions = rule.api_versions
                if "*" in kinds or "*" in versions:
                    targets = sorted(
                        g
                        for g in self._known_gvks()
                        if rule.matches_target(*g.rsplit("/", 1))
                    )
                else:
                    targets = [f"{v}/{k}" for v in versions for k in kinds]
                for facade_op, (wire_op, method) in _WIRE_OPS.items():
                    if not rule.matches_operation(wire_op):
                        continue
                    for gvk in targets:
                        fn = getattr(client, method)
                        self.interpreter.register_webhook(gvk, facade_op, fn)
                        regs.append((gvk, facade_op, fn))
        self._registered[key] = regs
        # hook changes re-run the pipeline for affected templates so
        # bindings built with the old semantics are rebuilt (same full
        # resync the declarative configmanager performs)
        self._resync(affected_gvks | {gvk for gvk, _, _ in regs})
        return DONE

    def _resync(self, gvks: set[str]) -> None:
        for res in self.store.list("Resource"):
            if f"{res.api_version}/{res.kind}" in gvks:
                self.store.apply(res)
