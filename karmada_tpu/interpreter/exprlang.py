"""Sandboxed expression language for declarative interpreter customizations.

Ref: pkg/resourceinterpreter/customized/declarative/luavm/lua.go:46-316 —
the reference embeds a gopher-lua VM so a ResourceInterpreterCustomization
CR can carry arbitrary per-kind logic (conditional status math, replica
derivation across fields, health predicates). The path-DSL
(interpreter/declarative.py) covers the common shapes; this module closes
the expression-completeness gap with a restricted-Python evaluator:

- scripts are parsed with ``ast`` and validated against a node whitelist at
  registration time (no imports, no attribute access to dunders, no
  exec/eval, no comprehension of arbitrary builtins);
- execution walks the AST directly (never CPython ``eval``/``exec``), so
  the sandbox boundary is this interpreter, not CPython's; a fuel counter
  bounds runaway loops (the VM-pool + instruction-budget analogue of the
  reference's lua.go:279-287 context cancellation);
- dict values support attribute-style access (``obj.spec.replicas`` ==
  ``obj["spec"]["replicas"]``) so ported reference scripts keep their
  shape; missing fields read as ``None`` (Lua nil semantics) instead of
  raising, which is what interpreter scripts overwhelmingly want;
- the function-per-operation contract mirrors the reference exactly:
  ``GetReplicas(observedObj)``, ``ReviseReplica(desiredObj, replica)``,
  ``Retain(desiredObj, observedObj)``, ``AggregateStatus(desiredObj,
  statusItems)``, ``InterpretHealth(observedObj)``,
  ``ReflectStatus(observedObj)``, ``GetDependencies(desiredObj)``.

A small ``kube`` helper namespace provides the reference's kube.lua
equivalents (getResourceQuantity, accuratePodRequirements,
getPodDependencies).
"""

from __future__ import annotations

import ast
import math
from typing import Any, Callable, Optional

MAX_FUEL = 500_000  # AST-step budget per invocation
MAX_ITERATIONS = 100_000  # per-loop bound
# Single-value size ceiling (chars / elements). Fuel meters AST steps, not
# the cost of one step: every op that can grow a value at C speed (seq
# concat, repetition, extend/replace/join) is pre-checked against this cap
# BEFORE allocating, so `s = s + s` doubling cannot outrun the fuel meter.
MAX_VALUE_SIZE = 10**7

_ALLOWED_NODES = (
    ast.Module, ast.FunctionDef, ast.arguments, ast.arg, ast.Return,
    ast.If, ast.For, ast.While, ast.Break, ast.Continue, ast.Pass,
    ast.Assign, ast.AugAssign, ast.Expr,
    ast.BoolOp, ast.BinOp, ast.UnaryOp, ast.Compare, ast.IfExp,
    ast.Call, ast.keyword,
    ast.Attribute, ast.Subscript, ast.Slice, ast.Index if hasattr(ast, "Index") else ast.Slice,
    ast.Name, ast.Load, ast.Store, ast.Constant,
    ast.Dict, ast.List, ast.Tuple, ast.Set,
    ast.ListComp, ast.DictComp, ast.GeneratorExp, ast.comprehension,
    ast.JoinedStr, ast.FormattedValue,
    ast.And, ast.Or, ast.Not, ast.USub, ast.UAdd,
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod, ast.Pow,
    ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
    ast.In, ast.NotIn, ast.Is, ast.IsNot,
)


class ScriptError(Exception):
    """Raised for invalid scripts (registration time) and runtime faults
    (bad field math, fuel exhaustion) — the configmanager surfaces these on
    the customization CR, mirroring the reference's Lua error conditions."""


class _Missing:
    """Lua-nil-style chainable missing value: attribute/index reads on a
    missing field stay missing, truthiness is False, equality only with
    None/missing."""

    _instance: Optional["_Missing"] = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __bool__(self):
        return False

    def __eq__(self, other):
        return other is None or isinstance(other, _Missing)

    def __ne__(self, other):
        return not self.__eq__(other)

    def __hash__(self):
        return hash(None)

    def __repr__(self):
        return "nil"


NIL = _Missing()


def _is_nil(v: Any) -> bool:
    return v is None or isinstance(v, _Missing)


def _de_nil(v: Any) -> Any:
    """Convert NIL back to None at the script boundary (recursively for
    containers the script built)."""
    if isinstance(v, _Missing):
        return None
    if isinstance(v, dict):
        return {k: _de_nil(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_de_nil(x) for x in v]
    if isinstance(v, tuple):
        return tuple(_de_nil(x) for x in v)
    return v


def _kube_get_resource_quantity(q: Any) -> float:
    """kube.getResourceQuantity: parse a k8s quantity into a float of its
    base unit (cpu quantities -> cores, memory -> bytes)."""
    from ..utils.quantity import parse_quantity

    if _is_nil(q):
        return 0.0
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q)
    # cpu milli style handled by parse_quantity("cpu"); binary/decimal
    # suffixes by the memory parser — choose by suffix shape
    if s.endswith("m") and s[:-1].replace(".", "", 1).isdigit():
        return parse_quantity(s, "cpu") / 1000.0
    try:
        return float(s)
    except ValueError:
        return float(parse_quantity(s, "memory"))


def _kube_accurate_pod_requirements(template: Any) -> dict:
    from .native import pod_requests

    template = _de_nil(template) or {}
    return {"resourceRequest": pod_requests(template.get("spec") or {})}


def _kube_get_pod_dependencies(template: Any, namespace: Any = "") -> list:
    from .native import pod_spec_dependencies

    template = _de_nil(template) or {}
    return [
        {
            "apiVersion": d.api_version,
            "kind": d.kind,
            "namespace": d.namespace or (_de_nil(namespace) or ""),
            "name": d.name,
        }
        for d in pod_spec_dependencies(
            template.get("spec") or {}, _de_nil(namespace) or ""
        )
    ]


def _bounded_sum(iterable, start=0):
    # sum() with a sequence start concatenates at C speed in one AST step;
    # numeric sums over bounded iterables are fine, sequence accumulation
    # must respect the value-size cap
    if isinstance(start, (str, list, tuple)):
        items = list(iterable)
        total = len(start) + sum(
            len(x) for x in items if isinstance(x, (str, list, tuple))
        )
        if total > MAX_VALUE_SIZE:
            raise ScriptError("sum result too large")
        return sum(items, start)
    return sum(iterable, start)


_SAFE_BUILTINS: dict[str, Any] = {
    "len": lambda x: 0 if _is_nil(x) else len(x),
    "min": min,
    "max": max,
    "sum": _bounded_sum,
    "abs": abs,
    "round": round,
    "int": lambda x=0: 0 if _is_nil(x) else int(x),
    "float": lambda x=0.0: 0.0 if _is_nil(x) else float(x),
    "str": lambda x="": "" if _is_nil(x) else str(x),
    "bool": lambda x=False: bool(x),
    "sorted": sorted,
    # the fuel counter meters AST steps; an unbounded range handed to
    # sum/list/sorted would run at C speed outside it
    "range": lambda *a: _bounded_range(*a),
    "enumerate": enumerate,
    "any": any,
    "all": all,
    "dict": dict,
    "list": lambda x=(): [] if _is_nil(x) else list(x),
    "tuple": tuple,
    "set": set,
    "math": math,  # module access guarded by the attribute whitelist below
}

_MATH_ALLOWED = {"ceil", "floor", "sqrt", "inf", "nan", "pow", "log", "log2"}


class _Env:
    __slots__ = ("vars", "parent")

    def __init__(self, parent=None):
        self.vars: dict[str, Any] = {}
        self.parent = parent

    def get(self, name: str) -> Any:
        env = self
        while env is not None:
            if name in env.vars:
                return env.vars[name]
            env = env.parent
        if name in _SAFE_BUILTINS:
            return _SAFE_BUILTINS[name]
        raise ScriptError(f"name {name!r} is not defined")

    def set(self, name: str, value: Any) -> None:
        env = self
        while env is not None:
            if name in env.vars:
                env.vars[name] = value
                return
            env = env.parent
        self.vars[name] = value


class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class _Function:
    __slots__ = ("node", "closure", "vm")

    def __init__(self, node: ast.FunctionDef, closure: "_Env", vm: "ExprVM"):
        self.node = node
        self.closure = closure
        self.vm = vm

    def __call__(self, *args):
        params = [a.arg for a in self.node.args.args]
        defaults = self.node.args.defaults
        env = _Env(self.closure)
        n_required = len(params) - len(defaults)
        for i, p in enumerate(params):
            if i < len(args):
                env.vars[p] = args[i]
            elif i >= n_required:
                env.vars[p] = self.vm._eval(defaults[i - n_required], self.closure)
            else:
                env.vars[p] = NIL  # Lua-style: missing args are nil
        try:
            for stmt in self.node.body:
                self.vm._exec(stmt, env)
        except _Return as r:
            return r.value
        return None


class ExprVM:
    """One validated script: namespace of user functions + evaluator."""

    def __init__(self, source: str, extra_globals: Optional[dict] = None):
        try:
            tree = ast.parse(source, mode="exec")
        except SyntaxError as e:
            raise ScriptError(f"script syntax error: {e}") from e
        self._validate(tree)
        self.fuel = MAX_FUEL  # top-level statements run at registration
        self.globals = _Env()
        self.globals.vars["kube"] = _KubeNamespace()
        if extra_globals:
            self.globals.vars.update(extra_globals)
        for stmt in tree.body:
            if isinstance(stmt, ast.FunctionDef):
                self.globals.vars[stmt.name] = _Function(stmt, self.globals, self)
            elif isinstance(stmt, (ast.Assign, ast.Expr)):
                self._exec(stmt, self.globals)
            else:
                raise ScriptError(
                    f"top level only allows function/assignment, got "
                    f"{type(stmt).__name__}"
                )

    # -- validation --------------------------------------------------------

    @staticmethod
    def _validate(tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, _ALLOWED_NODES):
                raise ScriptError(
                    f"forbidden construct {type(node).__name__} in script"
                )
            if isinstance(node, ast.Attribute) and node.attr.startswith("_"):
                raise ScriptError(f"forbidden attribute {node.attr!r}")
            if isinstance(node, ast.Name) and node.id.startswith("__"):
                raise ScriptError(f"forbidden name {node.id!r}")
            if isinstance(node, ast.FunctionDef) and (
                node.decorator_list
                or node.args.vararg
                or node.args.kwarg
                or node.args.kwonlyargs
            ):
                raise ScriptError(
                    "decorators/varargs are not allowed in scripts"
                )

    # -- public ------------------------------------------------------------

    def has(self, name: str) -> bool:
        return name in self.globals.vars

    def call(self, name: str, *args) -> Any:
        fn = self.globals.vars.get(name)
        if not isinstance(fn, _Function):
            raise ScriptError(f"script defines no function {name!r}")
        self.fuel = MAX_FUEL
        try:
            return _de_nil(fn(*args))
        except (_Break, _Continue):
            raise ScriptError("break/continue outside loop")
        except ScriptError:
            raise
        except Exception as e:  # arithmetic on nil, bad indexes, ...
            raise ScriptError(f"script runtime error in {name}: {e}") from e

    # -- execution ---------------------------------------------------------

    def _burn(self) -> None:
        self.fuel -= 1
        if self.fuel <= 0:
            raise ScriptError("script exceeded its execution budget")

    def _exec(self, node: ast.stmt, env: _Env) -> None:
        self._burn()
        if isinstance(node, ast.Expr):
            self._eval(node.value, env)
        elif isinstance(node, ast.Assign):
            value = self._eval(node.value, env)
            for tgt in node.targets:
                self._assign(tgt, value, env)
        elif isinstance(node, ast.AugAssign):
            current = self._eval_target(node.target, env)
            value = self._apply_binop(node.op, current, self._eval(node.value, env))
            self._assign(node.target, value, env)
        elif isinstance(node, ast.Return):
            raise _Return(self._eval(node.value, env) if node.value else None)
        elif isinstance(node, ast.If):
            branch = node.body if self._eval(node.test, env) else node.orelse
            for stmt in branch:
                self._exec(stmt, env)
        elif isinstance(node, ast.While):
            count = 0
            while self._eval(node.test, env):
                count += 1
                if count > MAX_ITERATIONS:
                    raise ScriptError("while loop exceeded iteration bound")
                try:
                    for stmt in node.body:
                        self._exec(stmt, env)
                except _Break:
                    break
                except _Continue:
                    continue
        elif isinstance(node, ast.For):
            iterable = self._eval(node.iter, env)
            if _is_nil(iterable):
                iterable = ()
            count = 0
            for item in iterable:
                count += 1
                if count > MAX_ITERATIONS:
                    raise ScriptError("for loop exceeded iteration bound")
                self._assign(node.target, item, env)
                try:
                    for stmt in node.body:
                        self._exec(stmt, env)
                except _Break:
                    break
                except _Continue:
                    continue
            else:
                for stmt in node.orelse:
                    self._exec(stmt, env)
        elif isinstance(node, ast.FunctionDef):
            env.set(node.name, _Function(node, env, self))
        elif isinstance(node, ast.Break):
            raise _Break()
        elif isinstance(node, ast.Continue):
            raise _Continue()
        elif isinstance(node, ast.Pass):
            pass
        else:
            raise ScriptError(f"unsupported statement {type(node).__name__}")

    def _assign(self, target: ast.expr, value: Any, env: _Env) -> None:
        if isinstance(target, ast.Name):
            env.set(target.id, value)
        elif isinstance(target, ast.Attribute):
            obj = self._eval(target.value, env)
            if isinstance(obj, dict):
                obj[target.attr] = value
            else:
                raise ScriptError(
                    f"cannot set attribute {target.attr!r} on {type(obj).__name__}"
                )
        elif isinstance(target, ast.Subscript):
            obj = self._eval(target.value, env)
            key = self._eval(target.slice, env)
            obj[key] = value
        elif isinstance(target, (ast.Tuple, ast.List)):
            items = list(value)
            if len(items) != len(target.elts):
                raise ScriptError("unpack length mismatch")
            for tgt, item in zip(target.elts, items):
                self._assign(tgt, item, env)
        else:
            raise ScriptError(f"cannot assign to {type(target).__name__}")

    def _eval_target(self, target: ast.expr, env: _Env) -> Any:
        return self._eval(target, env)

    @staticmethod
    def _size_guard(left: Any, right: Any) -> None:
        """C-speed blowup guard: fuel meters AST steps, not the cost of one
        step, so big-int growth and sequence repetition must be bounded
        explicitly (x = x * x doubles digit count per fuel unit)."""
        if isinstance(left, int) and isinstance(right, int):
            if left.bit_length() + right.bit_length() > 1 << 16:
                raise ScriptError("integer operands too large")
        elif isinstance(left, (str, list, tuple)) and isinstance(right, int):
            if len(left) * max(right, 1) > MAX_VALUE_SIZE:
                raise ScriptError("sequence repetition too large")
        elif isinstance(right, (str, list, tuple)) and isinstance(left, int):
            if len(right) * max(left, 1) > MAX_VALUE_SIZE:
                raise ScriptError("sequence repetition too large")

    @staticmethod
    def _format_guard(fmt: str, args: Any) -> None:
        """'%999999999d' % 1 allocates ~1GB in one AST step. Bound the
        printf path: cap explicit width/precision digit runs in the format
        string, and cap the magnitude of int args when '*' (dynamic
        width/precision) appears."""
        import re

        if len(fmt) > MAX_VALUE_SIZE:
            raise ScriptError("format string too large")
        for width, precision in re.findall(
            r"%(?:\([^)]*\))?[-+ #0]*(\d*)(?:\.(\d*))?", fmt
        ):
            if (width and int(width) > 10**6) or (
                precision and int(precision) > 10**6
            ):
                raise ScriptError("format width too large")
        if "*" in fmt:
            seq = args if isinstance(args, tuple) else (args,)
            for a in seq:
                if isinstance(a, int) and abs(a) > 10**6:
                    raise ScriptError("dynamic format width too large")

    def _apply_binop(self, op: ast.operator, left: Any, right: Any) -> Any:
        if isinstance(op, ast.Add):
            if isinstance(left, (str, list, tuple)) and isinstance(
                right, (str, list, tuple)
            ):
                if len(left) + len(right) > MAX_VALUE_SIZE:
                    raise ScriptError("concatenation result too large")
            return left + right
        if isinstance(op, ast.Sub):
            return left - right
        if isinstance(op, ast.Mult):
            self._size_guard(left, right)
            return left * right
        if isinstance(op, ast.Div):
            return left / right
        if isinstance(op, ast.FloorDiv):
            return left // right
        if isinstance(op, ast.Mod):
            if isinstance(left, str):
                self._format_guard(left, right)
            return left % right
        if isinstance(op, ast.Pow):
            if abs(_num(right)) > 64:
                raise ScriptError("exponent too large")
            self._size_guard(left, left)
            return left ** right
        raise ScriptError(f"unsupported operator {type(op).__name__}")

    def _eval(self, node: ast.expr, env: _Env) -> Any:
        self._burn()
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Attribute):
            obj = self._eval(node.value, env)
            return self._getattr(obj, node.attr)
        if isinstance(node, ast.Subscript):
            obj = self._eval(node.value, env)
            if isinstance(node.slice, ast.Slice):
                lo = self._eval(node.slice.lower, env) if node.slice.lower else None
                hi = self._eval(node.slice.upper, env) if node.slice.upper else None
                return obj[lo:hi]
            key = self._eval(node.slice, env)
            if _is_nil(obj):
                return NIL
            if isinstance(obj, dict):
                return obj.get(key, NIL)
            try:
                return obj[key]
            except (IndexError, KeyError, TypeError):
                return NIL
        if isinstance(node, ast.BinOp):
            return self._apply_binop(
                node.op, self._eval(node.left, env), self._eval(node.right, env)
            )
        if isinstance(node, ast.BoolOp):
            if isinstance(node.op, ast.And):
                result: Any = True
                for value in node.values:
                    result = self._eval(value, env)
                    if not result:
                        return result
                return result
            for value in node.values:
                result = self._eval(value, env)
                if result:
                    return result
            return result
        if isinstance(node, ast.UnaryOp):
            operand = self._eval(node.operand, env)
            if isinstance(node.op, ast.Not):
                return not operand
            if isinstance(node.op, ast.USub):
                return -operand
            return +operand
        if isinstance(node, ast.Compare):
            left = self._eval(node.left, env)
            for op, comparator in zip(node.ops, node.comparators):
                right = self._eval(comparator, env)
                if not self._compare(op, left, right):
                    return False
                left = right
            return True
        if isinstance(node, ast.IfExp):
            return (
                self._eval(node.body, env)
                if self._eval(node.test, env)
                else self._eval(node.orelse, env)
            )
        if isinstance(node, ast.Call):
            fn = self._eval(node.func, env)
            args = [self._eval(a, env) for a in node.args]
            kwargs = {kw.arg: self._eval(kw.value, env) for kw in node.keywords}
            if not callable(fn):
                raise ScriptError(f"{fn!r} is not callable")
            return fn(*args, **kwargs)
        if isinstance(node, ast.Dict):
            return {
                self._eval(k, env): self._eval(v, env)
                for k, v in zip(node.keys, node.values)
            }
        if isinstance(node, ast.List):
            return [self._eval(e, env) for e in node.elts]
        if isinstance(node, ast.Tuple):
            return tuple(self._eval(e, env) for e in node.elts)
        if isinstance(node, ast.Set):
            return {self._eval(e, env) for e in node.elts}
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            out = []
            self._comprehend(node.generators, 0, env, lambda e: out.append(
                self._eval(node.elt, e)))
            return out
        if isinstance(node, ast.DictComp):
            out: dict = {}
            self._comprehend(node.generators, 0, env, lambda e: out.__setitem__(
                self._eval(node.key, e), self._eval(node.value, e)))
            return out
        if isinstance(node, ast.JoinedStr):
            parts = []
            for value in node.values:
                if isinstance(value, ast.FormattedValue):
                    v = _de_nil(self._eval(value.value, env))
                    # only nil renders empty (Lua semantics); 0/False print
                    parts.append("" if v is None else str(v))
                else:
                    parts.append(str(self._eval(value, env)))
            return "".join(parts)
        raise ScriptError(f"unsupported expression {type(node).__name__}")

    def _comprehend(self, generators, i, env: _Env, emit: Callable) -> None:
        if i == len(generators):
            emit(env)
            return
        gen = generators[i]
        iterable = self._eval(gen.iter, env)
        if _is_nil(iterable):
            iterable = ()
        count = 0
        for item in iterable:
            count += 1
            if count > MAX_ITERATIONS:
                raise ScriptError("comprehension exceeded iteration bound")
            inner = _Env(env)
            self._assign(gen.target, item, inner)
            if all(self._eval(cond, inner) for cond in gen.ifs):
                self._comprehend(generators, i + 1, inner, emit)

    @staticmethod
    def _compare(op: ast.cmpop, left: Any, right: Any) -> bool:
        if isinstance(op, ast.Eq):
            return left == right
        if isinstance(op, ast.NotEq):
            return left != right
        if isinstance(op, (ast.Is,)):
            return _is_nil(left) and _is_nil(right) if (
                _is_nil(left) or _is_nil(right)
            ) else left is right
        if isinstance(op, ast.IsNot):
            return not ExprVM._compare(ast.Is(), left, right)
        if _is_nil(left) or _is_nil(right):
            return False  # ordered compare with nil is never true
        if isinstance(op, ast.Lt):
            return left < right
        if isinstance(op, ast.LtE):
            return left <= right
        if isinstance(op, ast.Gt):
            return left > right
        if isinstance(op, ast.GtE):
            return left >= right
        if isinstance(op, ast.In):
            return left in right
        if isinstance(op, ast.NotIn):
            return left not in right
        raise ScriptError(f"unsupported comparison {type(op).__name__}")

    def _getattr(self, obj: Any, attr: str) -> Any:
        self._burn()
        if _is_nil(obj):
            return NIL
        if isinstance(obj, dict):
            return obj.get(attr, NIL)
        if obj is math:
            if attr not in _MATH_ALLOWED:
                raise ScriptError(f"math.{attr} is not allowed")
            return getattr(math, attr)
        if isinstance(obj, _KubeNamespace):
            return obj.get(attr)
        # whitelisted methods on concrete value types
        tp = type(obj)
        allowed = _METHOD_WHITELIST.get(tp)
        if allowed is not None and attr in allowed:
            bounded = _BOUNDED_METHODS.get((tp, attr))
            if bounded is not None:
                return lambda *args: bounded(obj, *args)
            return getattr(obj, attr)
        raise ScriptError(
            f"attribute {attr!r} is not allowed on {tp.__name__}"
        )


def _bounded_extend(obj: list, iterable: Any) -> None:
    items = list(iterable)
    if len(obj) + len(items) > MAX_VALUE_SIZE:
        raise ScriptError("extend result too large")
    obj.extend(items)


def _bounded_replace(obj: str, old: str, new: str, count: int = -1) -> str:
    # pre-check the worst-case result length before the C-speed allocation:
    # s.replace(a, s) multiplies len(s) by the occurrence count in one step
    old = str(old)
    new = str(new)
    if not old:
        occurrences = len(obj) + 1
    else:
        occurrences = obj.count(old)
    if count >= 0:
        occurrences = min(occurrences, count)
    grown = len(obj) + occurrences * max(len(new) - len(old), 0)
    if grown > MAX_VALUE_SIZE:
        raise ScriptError("replace result too large")
    return obj.replace(old, new, count)


def _bounded_join(obj: str, iterable: Any) -> str:
    parts = [str(p) for p in iterable]
    total = sum(len(p) for p in parts) + len(obj) * max(len(parts) - 1, 0)
    if total > MAX_VALUE_SIZE:
        raise ScriptError("join result too large")
    return obj.join(parts)


# growth-capable whitelisted methods routed through pre-checked wrappers;
# everything else on the whitelist is size-bounded by its receiver already
_BOUNDED_METHODS: dict[tuple[type, str], Callable] = {
    (list, "extend"): _bounded_extend,
    (str, "replace"): _bounded_replace,
    (str, "join"): _bounded_join,
}


_METHOD_WHITELIST: dict[type, frozenset] = {
    # NOTE: str.format / format_map are deliberately absent — the format
    # mini-language performs real attribute traversal ("{0.__class__}") and
    # would tunnel through the dunder ban; f-strings are safe because this
    # evaluator renders them itself
    str: frozenset({
        "lower", "upper", "strip", "startswith", "endswith", "split",
        "replace", "join", "find", "rstrip", "lstrip", "title",
    }),
    list: frozenset({"append", "extend", "insert", "pop", "remove",
                     "index", "count", "sort", "reverse"}),
    dict: frozenset({"get", "keys", "values", "items", "update", "pop",
                     "setdefault"}),
    set: frozenset({"add", "discard", "union", "intersection"}),
    tuple: frozenset({"index", "count"}),
}


class _KubeNamespace:
    """The reference's kube.lua helper surface."""

    _FNS = {
        "getResourceQuantity": _kube_get_resource_quantity,
        "accuratePodRequirements": _kube_accurate_pod_requirements,
        "getPodDependencies": _kube_get_pod_dependencies,
    }

    def get(self, name: str):
        fn = self._FNS.get(name)
        if fn is None:
            raise ScriptError(f"kube.{name} is not provided")
        return fn


def _num(v: Any) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


def _bounded_range(*args) -> range:
    r = range(*args)
    if len(r) > MAX_ITERATIONS:
        raise ScriptError("range too large")
    return r
