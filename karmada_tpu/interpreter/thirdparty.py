"""Built-in third-party CRD customizations (the embedded corpus).

Ref: pkg/resourceinterpreter/default/thirdparty/resourcecustomizations/**
(~30 Lua scripts embedded via embed.FS, loader thirdparty.go) — the kinds
the reference ships interpreter semantics for out of the box: OpenKruise
workloads, Argo Workflow, FluxCD sources/releases, Kyverno policies, Flink
deployments.

This build expresses the same semantics as declarative path-DSL rules
(:mod:`.declarative`) instead of an embedded VM, registered on the
``thirdparty`` tier of the facade chain: user customizations override them,
they override the native defaults (interpreter.go chain order).

Semantics per kind follow the reference scripts (cited inline), re-derived
field-by-field — e.g. CloneSet aggregates replica counters by sum and
revision strings by last-non-empty and only advances observedGeneration
when every member caught up; flux kinds retain member-written
``spec.suspend`` and are healthy on the Ready/True condition; argo Workflow
and kruise BroadcastJob default replicas to ``spec.parallelism or 1``.
"""

from __future__ import annotations

from .declarative import CustomizationRules, _compile
from .facade import ResourceInterpreter

_KRUISE_POD_DEPS = {"pod_template_path": "template"}

# api group per flux source kind (sourceRef.kind -> apiVersion)
_FLUX_SOURCE_GROUPS = {
    "GitRepository": "source.toolkit.fluxcd.io/v1",
    "HelmRepository": "source.toolkit.fluxcd.io/v1beta2",
    "HelmChart": "source.toolkit.fluxcd.io/v1beta2",
    "OCIRepository": "source.toolkit.fluxcd.io/v1beta2",
    "Bucket": "source.toolkit.fluxcd.io/v1beta2",
}

# flux source/release kinds share the suspend-retention + Ready-condition
# pattern (source-controller re-writes spec.suspend on the member;
# health = conditions[type=Ready].status == True, reason Succeeded)
def _flux_rules(reason: str = "Succeeded", extra_deps: list | None = None):
    return CustomizationRules(
        retain_paths=["suspend"],
        health=[{"condition": "Ready", "status": "True", "reason": reason}],
        status_paths=[
            "conditions", "observedGeneration", "artifact", "url",
            "lastHandledReconcileAt",
        ],
        status_aggregation={
            "observedGeneration": "min",
            "lastHandledReconcileAt": "last",
        },
        dependencies=extra_deps or [],
    )


THIRDPARTY_CUSTOMIZATIONS: dict[str, CustomizationRules] = {
    # ---- apps.kruise.io (CloneSet/customizations.yaml etc.) --------------
    "apps.kruise.io/v1alpha1/CloneSet": CustomizationRules(
        replica_path="replicas",
        pod_requests_path="template",
        health=[
            {"observed_generation": True},
            {"path": "updatedReplicas", "op": "==", "spec_path": "replicas"},
            {"path": "readyReplicas", "op": "==", "status_path": "replicas"},
        ],
        status_paths=[
            "replicas", "readyReplicas", "updatedReplicas", "availableReplicas",
            "updatedReadyReplicas", "expectedUpdatedReplicas", "observedGeneration",
            "meta.generation", "updateRevision", "currentRevision", "labelSelector",
        ],
        status_aggregation={
            "replicas": "sum",
            "readyReplicas": "sum",
            "updatedReplicas": "sum",
            "availableReplicas": "sum",
            "updatedReadyReplicas": "sum",
            "expectedUpdatedReplicas": "sum",
            "updateRevision": "last",
            "currentRevision": "last",
            "labelSelector": "last",
        },
        status_zero_fields=[
            "replicas", "readyReplicas", "updatedReplicas", "availableReplicas",
            "updatedReadyReplicas", "expectedUpdatedReplicas",
        ],
        aggregate_observed_generation=True,
        dependencies=[_KRUISE_POD_DEPS],
    ),
    "apps.kruise.io/v1beta1/StatefulSet": CustomizationRules(
        replica_path="replicas",
        pod_requests_path="template",
        health=[
            {"observed_generation": True},
            {"path": "updatedReplicas", "op": "==", "spec_path": "replicas"},
            {"path": "readyReplicas", "op": "==", "status_path": "replicas"},
        ],
        status_paths=[
            "replicas", "readyReplicas", "updatedReplicas", "availableReplicas",
            "currentReplicas", "observedGeneration", "meta.generation",
            "currentRevision", "updateRevision", "labelSelector",
        ],
        status_aggregation={
            "replicas": "sum",
            "readyReplicas": "sum",
            "updatedReplicas": "sum",
            "availableReplicas": "sum",
            "currentReplicas": "sum",
            "currentRevision": "last",
            "updateRevision": "last",
            "labelSelector": "last",
        },
        status_zero_fields=[
            "replicas", "readyReplicas", "updatedReplicas", "availableReplicas",
            "currentReplicas",
        ],
        aggregate_observed_generation=True,
        dependencies=[_KRUISE_POD_DEPS],
    ),
    "apps.kruise.io/v1alpha1/DaemonSet": CustomizationRules(
        health=[
            {"observed_generation": True},
            {
                "path": "numberReady",
                "op": "==",
                "status_path": "desiredNumberScheduled",
            },
        ],
        status_paths=[
            "currentNumberScheduled", "desiredNumberScheduled", "numberAvailable",
            "numberMisscheduled", "numberReady", "updatedNumberScheduled",
            "observedGeneration", "meta.generation", "daemonSetHash",
        ],
        status_aggregation={
            "currentNumberScheduled": "sum",
            "desiredNumberScheduled": "sum",
            "numberAvailable": "sum",
            "numberMisscheduled": "sum",
            "numberReady": "sum",
            "updatedNumberScheduled": "sum",
            "daemonSetHash": "last",
        },
        status_zero_fields=[
            "currentNumberScheduled", "desiredNumberScheduled", "numberAvailable",
            "numberMisscheduled", "numberReady", "updatedNumberScheduled",
        ],
        aggregate_observed_generation=True,
        dependencies=[_KRUISE_POD_DEPS],
    ),
    "apps.kruise.io/v1alpha1/BroadcastJob": CustomizationRules(
        replica_path="parallelism",
        replica_default=1,
        pod_requests_path="template",
        # healthy = desired > 0, no failures, and some pod active or done
        # (BroadcastJob Lua: desired==0 or failed!=0 -> false;
        #  succeeded==0 and active==0 -> false)
        health=[
            {"path": "desired", "op": ">=", "value": 1},
            {"path": "failed", "op": "==", "value": 0},
            {
                "any": [
                    {"path": "succeeded", "op": ">=", "value": 1},
                    {"path": "active", "op": ">=", "value": 1},
                ]
            },
        ],
        # member controllers write pod labels back into the template
        retain_paths=["template.metadata.labels"],
        status_paths=["active", "succeeded", "failed", "desired", "phase"],
        status_aggregation={
            "active": "sum",
            "succeeded": "sum",
            "failed": "sum",
            "desired": "sum",
            "phase": "last",
        },
        status_zero_fields=["active", "succeeded", "failed", "desired"],
        dependencies=[_KRUISE_POD_DEPS],
    ),
    "apps.kruise.io/v1alpha1/AdvancedCronJob": CustomizationRules(
        status_aggregation={
            "lastScheduleTime": "max",
            "type": "last",
        },
        dependencies=[
            {"pod_template_path": "template.jobTemplate.spec.template"},
            {"pod_template_path": "template.broadcastJobTemplate.spec.template"},
        ],
    ),
    # ---- argoproj.io (Workflow/customizations.yaml) ----------------------
    "argoproj.io/v1alpha1/Workflow": CustomizationRules(
        replica_path="parallelism",
        replica_default=1,
        # phase unset/''/Failed/Error -> unhealthy
        health=[
            {"path": "phase", "op": "in", "value": ["Pending", "Running", "Succeeded"]},
        ],
        # member controller owns suspend + the whole status
        retain_paths=["suspend"],
        retain_status=True,
        status_paths=["phase", "startedAt", "finishedAt", "progress"],
        status_aggregation={
            "phase": "last",
            "startedAt": "min",
            "finishedAt": "max",
            "progress": "last",
        },
    ),
    # ---- flink.apache.org (FlinkDeployment/customizations.yaml) ----------
    "flink.apache.org/v1beta1/FlinkDeployment": CustomizationRules(
        replica_path="jobManager.replicas",
        replica_default=1,
        # job state past CREATED/RECONCILING is healthy; while still
        # materializing only an ERROR job-manager deployment is "settled"
        health=[
            {
                "any": [
                    {
                        "path": "jobStatus.state",
                        "op": "in",
                        "value": ["RUNNING", "FINISHED", "SUSPENDED", "CANCELED"],
                    },
                    {"path": "jobManagerDeploymentStatus", "op": "==", "value": "ERROR"},
                ]
            },
        ],
        status_paths=[
            "jobStatus", "jobManagerDeploymentStatus", "lifecycleState", "error",
        ],
        status_aggregation={
            "jobManagerDeploymentStatus": "last",
            "lifecycleState": "last",
            "error": "last",
        },
    ),
    # ---- fluxcd ----------------------------------------------------------
    "helm.toolkit.fluxcd.io/v2beta1/HelmRelease": CustomizationRules(
        retain_paths=["suspend"],
        health=[
            {
                "condition": "Ready",
                "status": "True",
                "reason": "ReconciliationSucceeded",
            }
        ],
        status_paths=[
            "conditions", "observedGeneration", "lastAppliedRevision",
            "lastAttemptedRevision", "helmChart",
        ],
        status_aggregation={
            "observedGeneration": "min",
            "lastAppliedRevision": "last",
            "lastAttemptedRevision": "last",
        },
        dependencies=[
            # follow the chart source the release actually references
            # (sourceRef.kind is HelmRepository | GitRepository | Bucket)
            {
                "name_path": "chart.spec.sourceRef.name",
                "namespace_path": "chart.spec.sourceRef.namespace",
                "kind_path": "chart.spec.sourceRef.kind",
                "api_version_by_kind": _FLUX_SOURCE_GROUPS,
            },
            {"list_path": "valuesFrom", "name_field": "name", "kind_field": "kind"},
        ],
    ),
    "kustomize.toolkit.fluxcd.io/v1/Kustomization": CustomizationRules(
        retain_paths=["suspend"],
        health=[
            {"condition": "Ready", "status": "True", "reason": "ReconciliationSucceeded"}
        ],
        status_paths=[
            "conditions", "observedGeneration", "lastAppliedRevision",
            "lastAttemptedRevision", "inventory",
        ],
        status_aggregation={
            "observedGeneration": "min",
            "lastAppliedRevision": "last",
            "lastAttemptedRevision": "last",
        },
        dependencies=[
            # sourceRef.kind is GitRepository | OCIRepository | Bucket
            {
                "name_path": "sourceRef.name",
                "namespace_path": "sourceRef.namespace",
                "kind_path": "sourceRef.kind",
                "api_version_by_kind": _FLUX_SOURCE_GROUPS,
            },
        ],
    ),
    "source.toolkit.fluxcd.io/v1/GitRepository": _flux_rules(
        extra_deps=[{"kind": "Secret", "api_version": "v1", "name_path": "secretRef.name"}]
    ),
    "source.toolkit.fluxcd.io/v1beta2/Bucket": _flux_rules(
        extra_deps=[{"kind": "Secret", "api_version": "v1", "name_path": "secretRef.name"}]
    ),
    "source.toolkit.fluxcd.io/v1beta2/HelmChart": _flux_rules(
        "ChartPullSucceeded",
        extra_deps=[
            {
                "name_path": "sourceRef.name",
                "kind_path": "sourceRef.kind",
                "kind": "HelmRepository",
                "api_version_by_kind": _FLUX_SOURCE_GROUPS,
            }
        ],
    ),
    "source.toolkit.fluxcd.io/v1beta2/HelmRepository": _flux_rules(
        extra_deps=[{"kind": "Secret", "api_version": "v1", "name_path": "secretRef.name"}]
    ),
    "source.toolkit.fluxcd.io/v1beta2/OCIRepository": _flux_rules(
        extra_deps=[{"kind": "Secret", "api_version": "v1", "name_path": "secretRef.name"}]
    ),
    # ---- kyverno.io ------------------------------------------------------
    "kyverno.io/v1/Policy": CustomizationRules(
        health=[
            {
                "any": [
                    {"path": "ready", "op": "==", "value": True},
                    {"condition": "Ready", "status": "True", "reason": "Succeeded"},
                ]
            }
        ],
        status_paths=["ready", "conditions", "autogen", "rulecount"],
        status_aggregation={"ready": "and"},
    ),
}

# ClusterPolicy shares Policy's semantics (kyverno.io/v1/{Policy,ClusterPolicy})
THIRDPARTY_CUSTOMIZATIONS["kyverno.io/v1/ClusterPolicy"] = THIRDPARTY_CUSTOMIZATIONS[
    "kyverno.io/v1/Policy"
]


def register_thirdparty_interpreters(interp: ResourceInterpreter) -> None:
    """Install the embedded corpus on the thirdparty tier (thirdparty.go
    loader analogue)."""
    for gvk, rules in THIRDPARTY_CUSTOMIZATIONS.items():
        for op, fn in _compile(rules).items():
            interp.register_thirdparty(gvk, op, fn)
