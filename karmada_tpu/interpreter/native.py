"""Native default interpreters for built-in workload kinds.

Ref: pkg/resourceinterpreter/default/native/*.go — Go implementations for
Deployment/StatefulSet/DaemonSet/Job/Pod/... Replica extraction with
pod-template resource requests, per-kind status aggregation/health, retain
semantics, dependency discovery (configmaps/secrets/PVCs/service accounts).

Resource layout follows kube conventions inside the free-form spec/status
dicts (spec.replicas, spec.template.spec.containers[*].resources.requests).
"""

from __future__ import annotations

from ..utils.clone import clone_json, clone_resource
from datetime import datetime, timezone
from typing import Any, Optional

from ..api.core import Resource
from ..api.work import AggregatedStatusItem, NodeClaim, ReplicaRequirements
from ..utils.quantity import parse_quantity
from .facade import (
    AGGREGATE_STATUS,
    GET_DEPENDENCIES,
    GET_REPLICAS,
    INTERPRET_HEALTH,
    REFLECT_STATUS,
    RETAIN,
    REVISE_REPLICA,
    DependentObjectReference,
    ResourceInterpreter,
)

DEPLOYMENT = "apps/v1/Deployment"
STATEFULSET = "apps/v1/StatefulSet"
DAEMONSET = "apps/v1/DaemonSet"
JOB = "batch/v1/Job"
POD = "v1/Pod"

WORKLOAD_KINDS = (DEPLOYMENT, STATEFULSET, JOB, POD)


def pod_requests(pod_spec: dict) -> dict[str, int]:
    """Sum container resource requests in canonical units (the reference's
    ResourceRequest from pod template)."""
    total: dict[str, int] = {}
    for container in pod_spec.get("containers", []):
        for name, qty in container.get("resources", {}).get("requests", {}).items():
            total[name] = total.get(name, 0) + parse_quantity(qty, name)
    return total


def _template_pod_spec(obj: Resource) -> dict:
    return obj.spec.get("template", {}).get("spec", {})


def _node_claim(pod_spec: dict) -> Optional[NodeClaim]:
    selector = pod_spec.get("nodeSelector")
    tolerations = pod_spec.get("tolerations")
    if not selector and not tolerations:
        return None
    return NodeClaim(
        node_selector=dict(selector or {}), tolerations=list(tolerations or [])
    )


def _get_replicas_workload(obj: Resource) -> tuple[int, Optional[ReplicaRequirements]]:
    if _gvk(obj) == POD:
        replicas = 1
        pod_spec = obj.spec
    else:
        replicas = int(obj.spec.get("replicas", obj.spec.get("parallelism", 1)))
        pod_spec = _template_pod_spec(obj)
    reqs = ReplicaRequirements(
        resource_request=pod_requests(pod_spec),
        node_claim=_node_claim(pod_spec),
        namespace=obj.meta.namespace,
        priority_class_name=pod_spec.get("priorityClassName", ""),
    )
    return replicas, reqs


def _revise_replica(obj: Resource, replicas: int) -> Resource:
    out = clone_resource(obj)
    if _gvk(out) == JOB and "parallelism" in out.spec:
        out.spec["parallelism"] = replicas
    else:
        out.spec["replicas"] = replicas
    return out


def _reflect_status(obj: Resource) -> Optional[dict[str, Any]]:
    return obj.status or None


def _deployment_health(obj: Resource) -> bool:
    """deployment healthy: observed generation caught up and all replicas
    ready+updated (native/health.go semantics)."""
    st = obj.status or {}
    replicas = int(obj.spec.get("replicas", 0))
    return (
        int(st.get("readyReplicas", 0)) >= replicas
        and int(st.get("updatedReplicas", 0)) >= replicas
    )


def _pod_health(obj: Resource) -> bool:
    return (obj.status or {}).get("phase") in ("Running", "Succeeded")


def _job_health(obj: Resource) -> bool:
    st = obj.status or {}
    return int(st.get("failed", 0)) == 0


_SUM_FIELDS = {
    DEPLOYMENT: ("replicas", "readyReplicas", "updatedReplicas", "availableReplicas",
                 "unavailableReplicas"),
    STATEFULSET: ("replicas", "readyReplicas", "updatedReplicas", "availableReplicas"),
    DAEMONSET: ("currentNumberScheduled", "numberReady", "numberAvailable",
                "desiredNumberScheduled"),
    JOB: ("active", "succeeded", "failed"),
}


def _aggregate_status_sum(obj: Resource, items: list[AggregatedStatusItem]) -> Resource:
    """Per-kind numeric status aggregation across member clusters
    (native/aggregatestatus.go pattern: sum counters into the template)."""
    out = clone_resource(obj)
    fields = _SUM_FIELDS.get(_gvk(obj), ())
    agg: dict[str, Any] = {f: 0 for f in fields}
    for item in items:
        st = item.status or {}
        for f in fields:
            agg[f] += int(st.get(f, 0))
    out.status = {**(out.status or {}), **agg}
    return out


def _aggregate_lb_ingress(obj: Resource, items: list[AggregatedStatusItem]) -> Resource:
    """Service(LoadBalancer)/Ingress: concatenate every member's
    status.loadBalancer.ingress, defaulting hostname to the member name so
    consumers can tell where each VIP came from
    (native/aggregatestatus.go:123-205). Non-LoadBalancer Services keep
    their status untouched."""
    out = clone_resource(obj)
    if _gvk(obj) == "v1/Service" and (obj.spec or {}).get("type") != "LoadBalancer":
        return out
    merged = []
    for item in items:
        for ing in ((item.status or {}).get("loadBalancer") or {}).get("ingress", []) or []:
            ing = dict(ing)
            if not ing.get("hostname"):
                ing["hostname"] = item.cluster_name
            merged.append(ing)
    out.status = {**(out.status or {}), "loadBalancer": {"ingress": merged}}
    return out


#: final-phase precedence (aggregatestatus.go:444-456): any Failed member
#: fails the whole pod; missing status counts as Pending
_POD_PHASE_ORDER = ("Failed", "Pending", "Running", "Succeeded")


def _aggregate_pod(obj: Resource, items: list[AggregatedStatusItem]) -> Resource:
    out = clone_resource(obj)
    phases = set()
    containers: list[dict] = []
    init_containers: list[dict] = []
    for item in items:
        st = item.status
        if not st:
            phases.add("Pending")
            continue
        phases.add(st.get("phase", "Pending"))
        for cs in st.get("containerStatuses", []) or []:
            containers.append({"ready": cs.get("ready", False),
                              "state": cs.get("state", {})})
        for cs in st.get("initContainerStatuses", []) or []:
            init_containers.append({"ready": cs.get("ready", False),
                                    "state": cs.get("state", {})})
    phase = next((p for p in _POD_PHASE_ORDER if p in phases), "Pending")
    out.status = {
        "phase": phase,
        "containerStatuses": containers,
        "initContainerStatuses": init_containers,
    }
    return out


def _aggregate_pvc(obj: Resource, items: list[AggregatedStatusItem]) -> Resource:
    """Bound only when every member is Bound; any Lost member loses the
    claim outright (aggregatestatus.go:521-557)."""
    out = clone_resource(obj)
    phase = "Bound"
    for item in items:
        p = (item.status or {}).get("phase")
        if p == "Lost":
            phase = "Lost"
            break
        if p and p != "Bound":
            phase = p
    out.status = {**(out.status or {}), "phase": phase}
    return out


def _aggregate_pdb(obj: Resource, items: list[AggregatedStatusItem]) -> Resource:
    """Sum healthy/expected/allowed counters; disruptedPods are namespaced
    by member name to stay distinguishable (aggregatestatus.go:559-600)."""
    out = clone_resource(obj)
    agg = {"currentHealthy": 0, "desiredHealthy": 0, "expectedPods": 0,
           "disruptionsAllowed": 0}
    disrupted: dict[str, Any] = {}
    for item in items:
        st = item.status or {}
        for f in agg:
            agg[f] += int(st.get(f, 0))
        for pod_name, when in (st.get("disruptedPods") or {}).items():
            disrupted[f"{item.cluster_name}/{pod_name}"] = when
    out.status = {**(out.status or {}), **agg, "disruptedPods": disrupted}
    return out


def _aggregate_hpa(obj: Resource, items: list[AggregatedStatusItem]) -> Resource:
    out = clone_resource(obj)
    agg = {"currentReplicas": 0, "desiredReplicas": 0}
    for item in items:
        st = item.status or {}
        for f in agg:
            agg[f] += int(st.get(f, 0))
    out.status = {**(out.status or {}), **agg}
    return out


def _ts_sort_key(val: str):
    """Parse an RFC3339 timestamp for chronological comparison. Raw string
    comparison is only chronological when every member emits identical
    formatting (Z vs +00:00, fractional seconds) — the reference compares
    parsed metav1.Time values (aggregatestatus.go:232-271)."""
    try:
        dt = datetime.fromisoformat(val.replace("Z", "+00:00"))
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        return dt
    except ValueError:
        return datetime.min.replace(tzinfo=timezone.utc)


def _aggregate_cronjob(obj: Resource, items: list[AggregatedStatusItem]) -> Resource:
    """Concatenate active job refs, keep the chronologically latest
    schedule/success times — aggregatestatus.go:232-271."""
    out = clone_resource(obj)
    active: list = []
    last_schedule = None
    last_success = None
    for item in items:
        st = item.status or {}
        active.extend(st.get("active", []) or [])
        for field, cur in (("lastScheduleTime", last_schedule),
                           ("lastSuccessfulTime", last_success)):
            val = st.get(field)
            if val and (cur is None or _ts_sort_key(val) > _ts_sort_key(cur)):
                if field == "lastScheduleTime":
                    last_schedule = val
                else:
                    last_success = val
    out.status = {**(out.status or {}), "active": active,
                  "lastScheduleTime": last_schedule,
                  "lastSuccessfulTime": last_success}
    return out


def _retain_default(desired: Resource, observed: Resource) -> Resource:
    """Keep member-side mutated fields the control plane must not stomp
    (native/retain.go): nodeName on pods, clusterIP on services, and
    member-HPA-owned replica counts (the hpaScaleTargetMarker label marks
    workloads whose replicas belong to the members)."""
    out = clone_resource(desired)
    if _gvk(desired) == POD:
        node_name = observed.spec.get("nodeName")
        if node_name:
            out.spec["nodeName"] = node_name
    if _gvk(desired) == "v1/Service":
        cluster_ip = observed.spec.get("clusterIP")
        if cluster_ip:
            out.spec["clusterIP"] = cluster_ip
    if (
        desired.meta.labels.get("resourcetemplate.karmada.io/retain-replicas")
        == "true"
        and "replicas" in observed.spec
    ):
        out.spec["replicas"] = observed.spec["replicas"]
    return out


def _get_dependencies(obj: Resource) -> list[DependentObjectReference]:
    """Dependencies from the pod template: configmaps/secrets/PVCs/service
    account (default/native/dependencies.go)."""
    pod_spec = obj.spec if _gvk(obj) == POD else _template_pod_spec(obj)
    return pod_spec_dependencies(pod_spec, obj.meta.namespace)


def pod_spec_dependencies(
    pod_spec: dict, ns: str
) -> list[DependentObjectReference]:
    """Walk a bare pod spec for referenced objects — shared with the
    declarative DSL's pod_template_path rule (kube.getPodDependencies)."""
    deps: list[DependentObjectReference] = []
    seen: set[tuple[str, str]] = set()

    def add(kind: str, api_version: str, name: str) -> None:
        if name and (kind, name) not in seen:
            seen.add((kind, name))
            deps.append(
                DependentObjectReference(
                    api_version=api_version, kind=kind, namespace=ns, name=name
                )
            )

    for vol in pod_spec.get("volumes", []):
        if "configMap" in vol:
            add("ConfigMap", "v1", vol["configMap"].get("name", ""))
        if "secret" in vol:
            add("Secret", "v1", vol["secret"].get("secretName", ""))
        if "persistentVolumeClaim" in vol:
            add("PersistentVolumeClaim", "v1",
                vol["persistentVolumeClaim"].get("claimName", ""))
    for container in pod_spec.get("containers", []):
        for env in container.get("env", []):
            ref = env.get("valueFrom", {})
            if "configMapKeyRef" in ref:
                add("ConfigMap", "v1", ref["configMapKeyRef"].get("name", ""))
            if "secretKeyRef" in ref:
                add("Secret", "v1", ref["secretKeyRef"].get("name", ""))
        for src in container.get("envFrom", []):
            if "configMapRef" in src:
                add("ConfigMap", "v1", src["configMapRef"].get("name", ""))
            if "secretRef" in src:
                add("Secret", "v1", src["secretRef"].get("name", ""))
    sa = pod_spec.get("serviceAccountName")
    if sa and sa != "default":
        add("ServiceAccount", "v1", sa)
    return deps


def _gvk(obj: Resource) -> str:
    return f"{obj.api_version}/{obj.kind}"


def register_native_interpreters(interp: ResourceInterpreter) -> None:
    for gvk in (DEPLOYMENT, STATEFULSET, DAEMONSET, JOB, POD):
        interp.register_native(gvk, GET_REPLICAS, _get_replicas_workload)
        interp.register_native(gvk, REVISE_REPLICA, _revise_replica)
        interp.register_native(gvk, AGGREGATE_STATUS, _aggregate_status_sum)
        interp.register_native(gvk, GET_DEPENDENCIES, _get_dependencies)
    # per-kind status aggregators beyond the counter sums
    # (native/aggregatestatus.go:123-645)
    interp.register_native("v1/Service", AGGREGATE_STATUS, _aggregate_lb_ingress)
    interp.register_native(
        "networking.k8s.io/v1/Ingress", AGGREGATE_STATUS, _aggregate_lb_ingress
    )
    interp.register_native(POD, AGGREGATE_STATUS, _aggregate_pod)
    interp.register_native(
        "v1/PersistentVolumeClaim", AGGREGATE_STATUS, _aggregate_pvc
    )
    interp.register_native(
        "policy/v1/PodDisruptionBudget", AGGREGATE_STATUS, _aggregate_pdb
    )
    interp.register_native(
        "autoscaling/v2/HorizontalPodAutoscaler", AGGREGATE_STATUS, _aggregate_hpa
    )
    interp.register_native("batch/v1/CronJob", AGGREGATE_STATUS, _aggregate_cronjob)
    interp.register_native("*", REFLECT_STATUS, _reflect_status)
    interp.register_native("*", RETAIN, _retain_default)
    interp.register_native(DEPLOYMENT, INTERPRET_HEALTH, _deployment_health)
    interp.register_native(STATEFULSET, INTERPRET_HEALTH, _deployment_health)
    interp.register_native(POD, INTERPRET_HEALTH, _pod_health)
    interp.register_native(JOB, INTERPRET_HEALTH, _job_health)
