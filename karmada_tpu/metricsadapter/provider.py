"""Metrics providers fanning out to member clusters — the three metrics API
flavors of the reference adapter.

Ref: pkg/metricsadapter/provider/
- resourcemetrics.go (metrics.k8s.io): PodMetrics/NodeMetrics queried by
  name or by label selector from every member in parallel, returned as one
  combined list with the owning cluster attached
  (queryPodMetricsByName:167, queryPodMetricsBySelector:205,
  queryNodeMetricsByName:260, queryNodeMetricsBySelector:297).
- custommetrics.go (custom.metrics.k8s.io): GetMetricByName:64 /
  GetMetricBySelector:113 fan out per cluster with BOTH an object label
  selector and a metric label selector, uniting the per-cluster
  MetricValueLists; ListAllMetrics:280 unions each member's discovered
  (group-resource, metric, namespaced) infos.
- externalmetrics.go: the reference STUBS this flavor ("karmada-
  metrics-adapter still not implement it", externalmetrics.go:38); this
  build implements it — namespaced external series filtered by a label
  selector, summed per the external-metrics contract.

The member-side sources are the MemberCluster metric surfaces
(pod_metrics_detail / node_metrics / custom_metric_series /
external_metric_series — the stand-ins for the per-cluster metrics API
servers); a real deployment swaps those for API clients, the merge
semantics are here.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api.policy import LabelSelector
from ..utils.member import MemberClientRegistry


@dataclass
class MetricValue:
    """One sample, cluster-attributed (the reference annotates the owning
    cluster onto each returned item)."""

    cluster: str
    value: float
    labels: dict[str, str] = field(default_factory=dict)
    object_name: str = ""
    namespace: str = ""
    metric: str = ""


@dataclass
class CustomMetricInfo:
    group_resource: str
    metric: str
    namespaced: bool = True

    def __hash__(self):
        return hash((self.group_resource, self.metric, self.namespaced))


def _selector_matches(selector, labels: dict) -> bool:
    if selector is None:
        return True
    if isinstance(selector, dict):
        selector = LabelSelector(match_labels=selector)
    return selector.matches(labels or {})


class ResourceMetricsProvider:
    """metrics.k8s.io flavor: pods/nodes by name or selector, all members."""

    def __init__(self, members: MemberClientRegistry) -> None:
        self.members = members

    def _fan_out(self):
        for name in self.members.names():
            member = self.members.get(name)
            if member is not None and member.reachable:
                yield name, member

    def pod_metrics_by_name(self, namespace: str, name: str) -> list[MetricValue]:
        key = f"{namespace}/{name}" if namespace else name
        out = []
        for cluster, member in self._fan_out():
            sample = member.pod_metrics_detail.get(key)
            if sample:
                out.append(
                    MetricValue(
                        cluster=cluster,
                        value=float(sample.get("cpu", 0.0)),
                        labels=dict(sample.get("labels") or {}),
                        object_name=name,
                        namespace=namespace,
                        metric="cpu",
                    )
                )
        return out

    def pod_metrics_by_selector(
        self, namespace: str, selector=None
    ) -> list[MetricValue]:
        out = []
        prefix = f"{namespace}/" if namespace else ""
        for cluster, member in self._fan_out():
            for key, sample in member.pod_metrics_detail.items():
                if namespace and not key.startswith(prefix):
                    continue
                if not _selector_matches(selector, sample.get("labels")):
                    continue
                out.append(
                    MetricValue(
                        cluster=cluster,
                        value=float(sample.get("cpu", 0.0)),
                        labels=dict(sample.get("labels") or {}),
                        object_name=key.rpartition("/")[2],
                        namespace=namespace,
                        metric="cpu",
                    )
                )
        return out

    def node_metrics_by_name(self, name: str) -> list[MetricValue]:
        out = []
        for cluster, member in self._fan_out():
            sample = member.node_metrics.get(name)
            if sample:
                out.append(
                    MetricValue(
                        cluster=cluster,
                        value=float(sample.get("cpu", 0.0)),
                        labels=dict(sample.get("labels") or {}),
                        object_name=name,
                        metric="cpu",
                    )
                )
        return out

    def node_metrics_by_selector(self, selector=None) -> list[MetricValue]:
        out = []
        for cluster, member in self._fan_out():
            for name, sample in member.node_metrics.items():
                if not _selector_matches(selector, sample.get("labels")):
                    continue
                out.append(
                    MetricValue(
                        cluster=cluster,
                        value=float(sample.get("cpu", 0.0)),
                        labels=dict(sample.get("labels") or {}),
                        object_name=name,
                        metric="cpu",
                    )
                )
        return out


class CustomMetricsProvider:
    """custom.metrics.k8s.io flavor: object + metric label selectors,
    namespaced and root-scoped, per-cluster lists united."""

    def __init__(self, members: MemberClientRegistry) -> None:
        self.members = members

    def _series(self):
        for name in self.members.names():
            member = self.members.get(name)
            if member is None or not member.reachable:
                continue
            for s in member.custom_metric_series:
                yield name, s

    @staticmethod
    def _ns_match(s: dict, namespace: str) -> bool:
        if not namespace:
            return not s.get("namespaced", True)
        return s.get("namespaced", True) and s.get("namespace", "") == namespace

    def get_metric_by_name(
        self,
        resource: str,
        namespace: str,
        name: str,
        metric: str,
        metric_selector=None,
    ) -> list[MetricValue]:
        out = []
        for cluster, s in self._series():
            if (
                s.get("resource") != resource
                or s.get("metric") != metric
                or s.get("object") != name
                or not self._ns_match(s, namespace)
                or not _selector_matches(metric_selector, s.get("labels"))
            ):
                continue
            out.append(
                MetricValue(
                    cluster=cluster,
                    value=float(s.get("value", 0.0)),
                    labels=dict(s.get("labels") or {}),
                    object_name=name,
                    namespace=namespace,
                    metric=metric,
                )
            )
        return out

    def get_metric_by_selector(
        self,
        resource: str,
        namespace: str,
        metric: str,
        object_selector=None,
        metric_selector=None,
    ) -> list[MetricValue]:
        out = []
        for cluster, s in self._series():
            if (
                s.get("resource") != resource
                or s.get("metric") != metric
                or not self._ns_match(s, namespace)
                or not _selector_matches(object_selector, s.get("object_labels"))
                or not _selector_matches(metric_selector, s.get("labels"))
            ):
                continue
            out.append(
                MetricValue(
                    cluster=cluster,
                    value=float(s.get("value", 0.0)),
                    labels=dict(s.get("labels") or {}),
                    object_name=s.get("object", ""),
                    namespace=namespace,
                    metric=metric,
                )
            )
        return out

    def list_all_metrics(self) -> set[CustomMetricInfo]:
        infos = set()
        for _, s in self._series():
            infos.add(
                CustomMetricInfo(
                    group_resource=s.get("resource", "pods"),
                    metric=s.get("metric", ""),
                    namespaced=bool(s.get("namespaced", True)),
                )
            )
        return infos


class ExternalMetricsProvider:
    """external.metrics.k8s.io flavor. The reference stubs this whole
    provider (externalmetrics.go:38); implemented here: namespaced series
    filtered by a label selector, one value per matching series."""

    def __init__(self, members: MemberClientRegistry) -> None:
        self.members = members

    def get_external_metric(
        self, namespace: str, metric: str, selector=None
    ) -> list[MetricValue]:
        out = []
        for name in self.members.names():
            member = self.members.get(name)
            if member is None or not member.reachable:
                continue
            for s in member.external_metric_series:
                if s.get("metric") != metric:
                    continue
                if namespace and s.get("namespace", "") != namespace:
                    continue
                if not _selector_matches(selector, s.get("labels")):
                    continue
                out.append(
                    MetricValue(
                        cluster=name,
                        value=float(s.get("value", 0.0)),
                        labels=dict(s.get("labels") or {}),
                        namespace=namespace,
                        metric=metric,
                    )
                )
        return out

    def external_metric_sum(
        self, namespace: str, metric: str, selector=None
    ) -> Optional[float]:
        samples = self.get_external_metric(namespace, metric, selector)
        if not samples:
            return None
        return sum(s.value for s in samples)

    def list_all_external_metrics(self) -> set[tuple[str, str]]:
        infos = set()
        for name in self.members.names():
            member = self.members.get(name)
            if member is None or not member.reachable:
                continue
            for s in member.external_metric_series:
                infos.add((s.get("namespace", ""), s.get("metric", "")))
        return infos


class MetricsAdapter:
    """Facade bundling the three providers (the adapter process)."""

    def __init__(self, members: MemberClientRegistry) -> None:
        self.members = members
        self.resources = ResourceMetricsProvider(members)
        self.custom = CustomMetricsProvider(members)
        self.external = ExternalMetricsProvider(members)

    # -- legacy workload-summary helpers (replica_calculator merge) --------

    def resource_metrics(self, workload_key: str) -> list[MetricValue]:
        """Per-cluster cpu utilization samples for a workload."""
        out = []
        for name in self.members.names():
            member = self.members.get(name)
            if member is None or not member.reachable:
                continue
            sample = member.pod_metrics.get(workload_key)
            if sample:
                out.append(
                    MetricValue(
                        cluster=name,
                        value=float(sample.get("cpu_utilization", 0.0)),
                        labels={"pods": str(sample.get("pods", 0))},
                    )
                )
        return out

    def merged_utilization(self, workload_key: str) -> Optional[float]:
        """Pod-weighted average across clusters (replica_calculator merge)."""
        samples = self.resource_metrics(workload_key)
        total_pods = sum(int(s.labels.get("pods", 0)) for s in samples)
        if total_pods == 0:
            return None
        return (
            sum(s.value * int(s.labels.get("pods", 0)) for s in samples) / total_pods
        )

    def custom_metric(self, metric_name: str) -> list[MetricValue]:
        """United per-cluster series for one metric (all scopes)."""
        return [
            MetricValue(cluster=c, value=float(s.get("value", 0.0)),
                        labels=dict(s.get("labels") or {}),
                        object_name=s.get("object", ""),
                        metric=metric_name)
            for c, s in self.custom._series()
            if s.get("metric") == metric_name
        ]

    def external_metric_sum(self, metric_name: str) -> Optional[float]:
        # external surface only, root scope: folding custom-metric series in
        # here double-counted a name present on both surfaces (and counted
        # per-object custom series into one scalar)
        return self.external.external_metric_sum("", metric_name)
