"""Metrics providers fanning out to member clusters.

The member-side sources are the MemberCluster metric surfaces
(pod_metrics for resource metrics, custom_metrics for custom/external);
a real deployment swaps those for metrics.k8s.io clients — the merge
semantics here mirror provider/resourcemetrics.go (sum/weighted-average
across clusters) and provider/custommetrics.go (per-cluster series united).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..utils.member import MemberClientRegistry


@dataclass
class MetricValue:
    cluster: str
    value: float
    labels: dict[str, str] = field(default_factory=dict)


class MetricsAdapter:
    def __init__(self, members: MemberClientRegistry) -> None:
        self.members = members

    # -- resource metrics (metrics.k8s.io flavor) --------------------------

    def resource_metrics(self, workload_key: str) -> list[MetricValue]:
        """Per-cluster cpu utilization samples for a workload."""
        out = []
        for name in self.members.names():
            member = self.members.get(name)
            if member is None or not member.reachable:
                continue
            sample = member.pod_metrics.get(workload_key)
            if sample:
                out.append(
                    MetricValue(
                        cluster=name,
                        value=float(sample.get("cpu_utilization", 0.0)),
                        labels={"pods": str(sample.get("pods", 0))},
                    )
                )
        return out

    def merged_utilization(self, workload_key: str) -> Optional[float]:
        """Pod-weighted average across clusters (replica_calculator merge)."""
        samples = self.resource_metrics(workload_key)
        total_pods = sum(int(s.labels.get("pods", 0)) for s in samples)
        if total_pods == 0:
            return None
        return (
            sum(s.value * int(s.labels.get("pods", 0)) for s in samples) / total_pods
        )

    # -- custom / external metrics -----------------------------------------

    def custom_metric(self, metric_name: str) -> list[MetricValue]:
        out = []
        for name in self.members.names():
            member = self.members.get(name)
            if member is None or not member.reachable:
                continue
            value = getattr(member, "custom_metrics", {}).get(metric_name)
            if value is not None:
                out.append(MetricValue(cluster=name, value=float(value)))
        return out

    def external_metric_sum(self, metric_name: str) -> Optional[float]:
        samples = self.custom_metric(metric_name)
        if not samples:
            return None
        return sum(s.value for s in samples)
