"""Metrics adapter: multi-cluster metrics aggregation APIs.

Ref: pkg/metricsadapter — implements custom-metrics, external-metrics and
resource-metrics (metrics.k8s.io) API flavors by fanning out to member
clusters and merging (provider/{custommetrics,externalmetrics,
resourcemetrics}.go). Feeds FederatedHPA.
"""

from .provider import MetricsAdapter  # noqa: F401
