"""Quickstart demo: the samples/nginx scenario end-to-end, then a failover.

Run from anywhere: python examples/quickstart.py
(uses CPU JAX; the scheduler kernels are the same programs bench.py runs on
TPU).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from karmada_tpu import cli
from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    new_deployment,
)
from karmada_tpu.utils.features import FAILOVER, feature_gate


def show(cp, key="default/nginx-deployment"):
    rb = cp.store.get("ResourceBinding", key)
    placed = {tc.name: tc.replicas for tc in rb.spec.clusters}
    print(f"  placement: {placed}")
    for item in rb.status.aggregated_status:
        print(f"  {item.cluster_name}: applied={item.applied} health={item.health}")


def main():
    feature_gate.set(FAILOVER, True)
    print("== local-up: 3 member clusters (member3 is Pull-mode)")
    cp = cli.cmd_local_up(3)

    print("== propagate nginx x6 with dynamic-weight division")
    cp.store.apply(new_deployment("nginx", replicas=6))
    cp.store.apply(
        PropagationPolicy(
            meta=ObjectMeta(name="nginx", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                placement=dynamic_weight_placement(),
            ),
        )
    )
    cp.settle()
    show(cp)

    print("== member1 becomes unreachable -> taint -> evict -> rehome")
    cp.members.get("member1").reachable = False
    cp.settle()
    show(cp)

    print("== replacements report healthy -> graceful eviction completes")
    rb = cp.store.get("ResourceBinding", "default/nginx-deployment")
    for tc in rb.spec.clusters:
        cp.members.get(tc.name).set_workload_status(
            "apps/v1/Deployment", "default", "nginx",
            {"replicas": tc.replicas, "readyReplicas": tc.replicas,
             "updatedReplicas": tc.replicas},
        )
    cp.settle()
    show(cp)

    print("== member1 recovers; trigger a fresh rebalance")
    cp.members.get("member1").reachable = True
    from karmada_tpu.controllers import (
        ObjectReferenceSelector,
        WorkloadRebalancer,
        WorkloadRebalancerSpec,
    )

    cp.settle()
    cp.store.apply(
        WorkloadRebalancer(
            meta=ObjectMeta(name="rebalance"),
            spec=WorkloadRebalancerSpec(
                workloads=[ObjectReferenceSelector(kind="Deployment", name="nginx")]
            ),
        )
    )
    cp.settle()
    show(cp)
    print("== describe")
    print(cli.cmd_describe(cp, "apps/v1/Deployment", "default", "nginx"))


if __name__ == "__main__":
    main()
