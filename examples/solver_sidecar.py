"""The solver as a standalone batch service (the ScoreAndAssign sidecar
shape of SURVEY.md §2.2): pack synthetic fleet + binding arrays, run ONE
fused jit step — estimator + min-merge + unified division — and unpack
placements. No control plane involved; this is the seam an out-of-tree
scheduler would call over gRPC.

Run from anywhere: python examples/solver_sidecar.py [--devices N]
(CPU JAX; pass XLA_FLAGS=--xla_force_host_platform_device_count=8 to see
the binding axis shard across virtual devices.)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp

from karmada_tpu.ops import DYNAMIC_WEIGHT
from karmada_tpu.parallel import schedule_step_interned


def main():
    rng = np.random.default_rng(0)
    n_bindings, n_clusters = 1024, 200

    # fleet snapshot: capacity in canonical integer units per dimension
    # (cpu-milli, memory bytes, pods)
    scales = np.asarray([512_000, 1 << 38, 1_000])
    available_cap = jnp.asarray(
        (rng.random((n_clusters, 3)) * scales[None, :]).astype(np.int64),
        jnp.int64,
    )
    has_summary = jnp.ones((n_clusters,), bool)

    # three request T-shirt sizes; every binding points at one (interning)
    profiles = jnp.asarray(
        [[250, 1 << 29, 1], [500, 1 << 30, 1], [1000, 2 << 30, 1]], jnp.int64
    )
    prof_idx = jnp.asarray(rng.integers(0, 3, size=n_bindings), jnp.int32)

    replicas = jnp.asarray(rng.integers(1, 50, size=n_bindings), jnp.int32)
    candidates = jnp.asarray(rng.random((n_bindings, n_clusters)) < 0.8)
    strategy = jnp.full((n_bindings,), DYNAMIC_WEIGHT, jnp.int32)
    static_w = jnp.zeros((n_bindings, n_clusters), jnp.int32)
    prev = jnp.zeros((n_bindings, n_clusters), jnp.int32)
    fresh = jnp.zeros((n_bindings,), bool)

    result = schedule_step_interned(
        available_cap, has_summary, profiles, prof_idx, strategy, replicas,
        candidates, static_w, prev, fresh, has_aggregated=False,
    )
    placed = np.asarray((result.assignment > 0).sum(axis=1))
    totals = np.asarray(result.assignment.sum(axis=1))
    ok = ~np.asarray(result.unschedulable)
    print(f"scheduled {ok.sum()}/{n_bindings} bindings on "
          f"{len(jax.devices())} device(s)")
    print(f"mean clusters/binding: {placed[ok].mean():.1f}")
    assert (totals[ok] == np.asarray(replicas)[ok]).all(), "replica totals drifted"
    print("replica totals preserved for every scheduled binding")


if __name__ == "__main__":
    main()
